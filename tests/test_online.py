"""OnlineTrainer (ISSUE 10): continuous online learning over the streaming
stack — staged ingest at zero steady-state compiles, versioned checkpoints,
train→serve hot-swap, watchdog-wired drift/NaN hooks with rollback, and the
chaos soak (slow-marked).
"""

import json
import time
import urllib.request

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import (
    DenseLayer,
    InputType,
    MultiLayerConfiguration,
    MultiLayerNetwork,
    OutputLayer,
    UpdaterConfig,
)
from deeplearning4j_tpu.runtime.checkpoint import CheckpointStore
from deeplearning4j_tpu.runtime.compile_manager import get_compile_manager
from deeplearning4j_tpu.runtime.online import (
    OnlineTrainer,
    clear_online_trainers,
    get_online_trainers,
)
from deeplearning4j_tpu.serving import InferenceService
from deeplearning4j_tpu.streaming import QueueSource, RecordSource
from deeplearning4j_tpu.telemetry import MetricsRegistry
from deeplearning4j_tpu.telemetry.flight_recorder import (
    FlightRecorder,
    set_flight_recorder,
)

FEATURES, CLASSES = 12, 4


def _net(seed=3):
    return MultiLayerNetwork(MultiLayerConfiguration(
        layers=[DenseLayer(n_out=16, activation="tanh"),
                OutputLayer(n_out=CLASSES, activation="softmax",
                            loss="mcxent")],
        input_type=InputType.feed_forward(FEATURES),
        updater=UpdaterConfig(updater="adam", learning_rate=1e-2),
        seed=seed)).init()


@pytest.fixture
def flight(tmp_path):
    rec = FlightRecorder(dump_dir=str(tmp_path / "flight"),
                         registry=MetricsRegistry())
    set_flight_recorder(rec)
    yield rec
    set_flight_recorder(None)


@pytest.fixture(autouse=True)
def _clean_trainers():
    yield
    clear_online_trainers()


def _producer(rng, w):
    def put(source, n, nan=False):
        for _ in range(n):
            x = rng.normal(size=FEATURES).astype(np.float32)
            if nan:
                x[:] = np.nan
            y = np.eye(CLASSES, dtype=np.float32)[int(np.argmax(x @ w))]
            source.put(x, y)
    return put


def _wait(pred, seconds=60.0):
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def _make(flight_dir_unused=None, **kw):
    rng = np.random.default_rng(0)
    put = _producer(rng, rng.normal(size=(FEATURES, CLASSES)))
    source = QueueSource(maxsize=8192)
    net = _net()
    defaults = dict(batch=16, stage=2, linger=0.05, registry=MetricsRegistry())
    defaults.update(kw)
    trainer = OnlineTrainer(net, source, **defaults)
    return trainer, source, put, net


class TestIngest:
    def test_trains_counts_and_stats(self, flight):
        trainer, source, put, net = _make(name="t-ingest")
        trainer.start()
        try:
            put(source, 96)
            assert _wait(lambda: trainer.stats()["records_total"] >= 96)
            # 96 records / batch 16 = 6 optimizer steps once fully drained
            assert _wait(lambda: trainer.stats()["steps_total"] >= 6)
            s = trainer.stats()
            assert s["alive"] and not s["paused"]
            assert s["steps_total"] == 6 and s["windows_total"] >= 2
            assert s["batches_total"] == 6
            assert net.iteration == s["steps_total"]
            assert s["loss_baseline"] is not None
            assert get_online_trainers()["t-ingest"] is trainer
        finally:
            trainer.stop()
        assert not trainer.alive

    def test_zero_steady_state_compiles_with_ragged_tail(self, flight):
        trainer, source, put, _ = _make(name="t-compiles")
        trainer.start()
        try:
            put(source, 64)  # warm: full windows + pre-warmed partials
            assert _wait(lambda: trainer.stats()["records_total"] >= 64)
            # the first DISPATCH warms the window family (incl. the pow2
            # partial variants) — mark compiles only after it happened
            assert _wait(lambda: trainer.stats()["steps_total"] >= 1)
            cm = get_compile_manager()
            before = cm.compiles.value
            put(source, 64)
            put(source, 9)  # ragged tail: partial batch AND partial window
            assert _wait(lambda: trainer.stats()["records_total"] >= 137)
            assert _wait(lambda: trainer.stats()["steps_total"] >= 9)
            assert cm.compiles.value - before == 0
        finally:
            trainer.stop()

    def test_padded_tail_masks_preserve_loss_semantics(self, flight):
        """A lone ragged micro-batch trains only its real rows: the masked
        window's first-step loss equals the unpadded batch's loss on the
        same params (mask-normalized losses, PR 3 contract)."""
        trainer, source, put, net = _make(name="t-mask")
        rng = np.random.default_rng(1)
        x = rng.normal(size=(5, FEATURES)).astype(np.float32)
        y = np.eye(CLASSES, dtype=np.float32)[rng.integers(0, CLASSES, 5)]
        from deeplearning4j_tpu.datasets.iterators import DataSet

        ref = _net()  # same seed: identical init params
        ref_loss = float(ref.score(DataSet(x, y)))
        trainer.start()
        try:
            for i in range(5):
                source.put(x[i], y[i])
            assert _wait(lambda: trainer.stats()["steps_total"] >= 1)
        finally:
            trainer.stop()
        first_loss = trainer.stats()["recent_window_losses"][0]
        assert first_loss == pytest.approx(ref_loss, rel=1e-5)

    def test_pause_resume_and_backpressure(self, flight):
        trainer, source, put, _ = _make(name="t-pause")
        trainer.start()
        try:
            put(source, 32)
            assert _wait(lambda: trainer.stats()["records_total"] >= 32)
            trainer.pause()
            put(source, 32)
            time.sleep(0.4)  # paused: the queue holds (at most one record
            # already mid-poll slips into the current micro-batch)
            assert trainer.stats()["records_total"] <= 33
            assert trainer.stats()["paused"]
            trainer.resume()
            assert _wait(lambda: trainer.stats()["records_total"] >= 64)
        finally:
            trainer.stop()

    def test_source_disconnect_reconnect_and_bad_records(self, flight):
        class Flaky(RecordSource):
            def __init__(self):
                self.q = QueueSource(maxsize=1024)
                self.fail_polls = 0

            def poll(self, timeout=0.1):
                if self.fail_polls > 0:
                    self.fail_polls -= 1
                    raise ConnectionError("down")
                return self.q.poll(timeout=timeout)

        rng = np.random.default_rng(0)
        put = _producer(rng, rng.normal(size=(FEATURES, CLASSES)))
        source = Flaky()
        trainer = OnlineTrainer(_net(), source, batch=16, stage=2,
                                linger=0.05, name="t-flaky",
                                source_retry_s=0.01,
                                registry=MetricsRegistry())
        trainer.start()
        try:
            source.q._q.put((None, None))  # unlabeled -> bad record
            put(source.q, 32)
            assert _wait(lambda: trainer.stats()["records_total"] >= 32)
            source.fail_polls = 5
            put(source.q, 32)
            assert _wait(lambda: trainer.stats()["records_total"] >= 64)
            s = trainer.stats()
            assert s["source_errors_total"] >= 1
            assert s["reconnects_total"] >= 1
            assert s["bad_records_total"] >= 1
            assert s["alive"]
        finally:
            trainer.stop()


class TestCheckpointAndSwap:
    def test_cadence_writes_versions_and_retention(self, flight, tmp_path):
        store = CheckpointStore(str(tmp_path / "ckpt"), retain=3,
                                registry=MetricsRegistry())
        trainer, source, put, _ = _make(name="t-ckpt",
                                        checkpoint_store=store,
                                        checkpoint_every_steps=4)
        trainer.start()
        try:
            put(source, 256)
            assert _wait(lambda: len(store.versions()) >= 3)
            assert _wait(lambda: trainer.stats()["records_total"] >= 256)
        finally:
            trainer.stop()
        versions = [v.version for v in store.versions()]
        assert len(versions) <= 3  # retention bound
        assert versions == sorted(versions)
        assert trainer.stats()["last_good_version"] in versions

    def test_hot_swap_serves_new_version_bit_exactly(self, flight, tmp_path):
        store = CheckpointStore(str(tmp_path / "ckpt"),
                                registry=MetricsRegistry())
        svc = InferenceService(registry=MetricsRegistry(), max_delay_ms=0.5)
        trainer, source, put, net = _make(
            name="t-swap", checkpoint_store=store, service=svc,
            serve_as="live")
        trainer.start()
        probe = np.random.default_rng(9).normal(
            size=(3, FEATURES)).astype(np.float32)
        try:
            put(source, 64)
            assert _wait(lambda: trainer.stats()["steps_total"] >= 4)
            served_v0 = np.asarray(svc.predict("live", probe, timeout_s=30))
            version = trainer.checkpoint_now(swap=True)
            store.join()
            served_v1 = np.asarray(svc.predict("live", probe, timeout_s=30))
            # the swap changed served predictions...
            assert np.abs(served_v1 - served_v0).max() > 0
            # ...to EXACTLY the checkpointed version's outputs (the served
            # clone and a fresh restore share the fast path + padding)
            from deeplearning4j_tpu.runtime import inference as _inf

            restored = store.restore(version)
            expect = _inf.mln_output(restored, probe)
            np.testing.assert_array_equal(served_v1, expect)
            assert svc.stats()["models"]["live"]["version"] == version
            assert trainer.stats()["swaps_total"] >= 1
        finally:
            trainer.stop()
            svc.stop()

    def test_swap_pays_zero_compiles(self, flight, tmp_path):
        store = CheckpointStore(str(tmp_path / "ckpt"),
                                registry=MetricsRegistry())
        svc = InferenceService(registry=MetricsRegistry(), max_delay_ms=0.5)
        trainer, source, put, _ = _make(
            name="t-swapc", checkpoint_store=store, service=svc,
            serve_as="live2")
        trainer.start()
        probe = np.zeros((2, FEATURES), np.float32)
        try:
            put(source, 64)
            assert _wait(lambda: trainer.stats()["steps_total"] >= 4)
            svc.warmup("live2", probe[:1])
            svc.predict("live2", probe, timeout_s=30)
            cm = get_compile_manager()
            before = cm.compiles.value
            trainer.checkpoint_now(swap=True)
            out = svc.predict("live2", probe, timeout_s=30)
            assert out.shape == (2, CLASSES)
            assert cm.compiles.value - before == 0
        finally:
            trainer.stop()
            svc.stop()


class TestDriftAndRollback:
    def test_nan_rollback_leaves_bundle_and_survives(self, flight, tmp_path):
        store = CheckpointStore(str(tmp_path / "ckpt"),
                                registry=MetricsRegistry())
        trainer, source, put, net = _make(
            name="t-nan", checkpoint_store=store, checkpoint_every_steps=4)
        trainer.start()
        try:
            put(source, 96)
            assert _wait(lambda: trainer.stats()["records_total"] >= 96)
            good = trainer.stats()["last_good_version"]
            assert good is not None
            put(source, 32, nan=True)
            assert _wait(lambda: trainer.stats()["rollbacks_total"] >= 1)
            assert trainer.alive
            assert flight.dumps, "rollback left no flight bundle"
            bundle = json.load(open(flight.dumps[-1]))
            kinds = {e["kind"] for e in bundle["events"]}
            assert "anomaly" in kinds and "online_rollback" in kinds
            # the live model is clean again (rolled back, not poisoned)
            leaves = jax.tree_util.tree_leaves(net.params)
            assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
            # and keeps training after the storm
            put(source, 64)
            steps = trainer.stats()["steps_total"]
            assert _wait(lambda: trainer.stats()["steps_total"] > steps)
        finally:
            trainer.stop()

    def test_loss_drift_detector_rolls_back(self, flight, tmp_path):
        """Unit-level: healthy windows set the baseline; a sustained loss
        jump emits loss-drift through the watchdog and rolls back. (The
        detector smooths over the last 3 window means, so a lone mild
        spike does NOT trigger — the jump must move the trend.)"""
        store = CheckpointStore(str(tmp_path / "ckpt"),
                                registry=MetricsRegistry())
        trainer, _, _, net = _make(name="t-drift", checkpoint_store=store,
                                   drift_factor=3.0, drift_min_windows=3)
        info = store.save(net)
        trainer._last_good_version = info.version
        for _ in range(4):
            trainer._check_window_health(np.full(4, 1.0))
        assert trainer._loss_baseline == pytest.approx(1.0)
        trainer._check_window_health(np.full(4, 5.0))  # mild lone spike
        assert trainer.stats()["rollbacks_total"] == 0
        trainer._check_window_health(np.full(4, 50.0))  # the trend moved
        assert trainer.stats()["rollbacks_total"] == 1
        assert trainer.stats()["anomalies"].get("loss-drift") == 1
        assert not trainer.paused  # default policy auto-resumes
        assert flight.dumps

    def test_adaptive_band_tolerates_heavy_tailed_noise(self, flight,
                                                        tmp_path):
        """Regression for the static-multiplier rule: a converged model with
        heavy-tailed per-example loss (mean ~.005, one mild outlier per
        window) hits a single hard-example window (mean .05). The old rule
        ``recent > factor * baseline`` fires on that window (.02 > 3 x .005
        = .015); the adaptive band scales with the EMA of the WITHIN-window
        variance the calm windows already exhibited, so it stays healthy."""
        store = CheckpointStore(str(tmp_path / "ckpt"),
                                registry=MetricsRegistry())
        trainer, _, _, net = _make(name="t-noise", checkpoint_store=store,
                                   drift_factor=3.0, drift_min_windows=3)
        info = store.save(net)
        trainer._last_good_version = info.version
        calm = np.array([0.0, 0.0, 0.0, 0.02])   # mean .005, std ~.0087
        hard = np.array([0.0, 0.0, 0.0, 0.2])    # mean .05: one hard example
        for _ in range(6):
            trainer._check_window_health(calm)
        assert trainer.stats()["loss_sigma"] == pytest.approx(
            float(np.std(calm)), rel=1e-6)
        baseline = trainer._loss_baseline
        # prove this scenario is a true distinguisher: the OLD static rule
        # would have flagged the hard window (trend .02 > 3 x baseline)
        old_limit = trainer.drift_factor * baseline
        recent_with_hard = float(np.mean([baseline, baseline, np.mean(hard)]))
        assert recent_with_hard > old_limit
        trainer._check_window_health(hard)
        for _ in range(4):
            trainer._check_window_health(calm)
        assert trainer.stats()["rollbacks_total"] == 0
        assert trainer.stats()["anomalies"] == {}

    def test_adaptive_band_still_catches_slow_drift(self, flight, tmp_path):
        """A genuine distribution shift moves every example together: window
        means creep up 1.4x per window while the per-window spread stays
        flat. The trend cannot widen the within-window band, so the
        detector fires within a bounded number of windows."""
        store = CheckpointStore(str(tmp_path / "ckpt"),
                                registry=MetricsRegistry())
        trainer, _, _, net = _make(name="t-creep", checkpoint_store=store,
                                   drift_factor=3.0, drift_min_windows=3)
        info = store.save(net)
        trainer._last_good_version = info.version
        spread = np.array([-0.01, 0.0, 0.0, 0.01])
        for _ in range(4):
            trainer._check_window_health(1.0 + spread)
        level, fired_at = 1.0, None
        for k in range(30):
            level *= 1.4
            trainer._check_window_health(level + spread)
            if trainer.stats()["anomalies"].get("loss-drift"):
                fired_at = k
                break
        assert fired_at is not None, "slow drift never tripped the band"
        assert fired_at <= 15
        assert trainer.stats()["rollbacks_total"] == 1

    def test_pause_on_policy_needs_explicit_resume(self, flight, tmp_path):
        store = CheckpointStore(str(tmp_path / "ckpt"),
                                registry=MetricsRegistry())
        trainer, _, _, net = _make(name="t-pauseon", checkpoint_store=store,
                                   drift_min_windows=2,
                                   pause_on=("loss-drift",))
        info = store.save(net)
        trainer._last_good_version = info.version
        for _ in range(3):
            trainer._check_window_health(np.full(4, 1.0))
        trainer._check_window_health(np.full(4, 99.0))
        assert trainer.paused
        trainer.resume()
        assert not trainer.paused

    def test_input_shift_detector_fires_event_only(self, flight):
        trainer, source, put, _ = _make(name="t-shift", shift_zscore=4.0)
        trainer.start()
        try:
            put(source, 128)
            assert _wait(lambda: trainer.stats()["records_total"] >= 128)
            # shifted distribution: mean jumps by ~40 sigma
            rng = np.random.default_rng(5)
            for _ in range(32):
                x = (rng.normal(size=FEATURES) + 50.0).astype(np.float32)
                source.put(x, np.eye(CLASSES, dtype=np.float32)[0])
            assert _wait(lambda: "input-shift"
                         in trainer.stats()["anomalies"])
            assert trainer.alive  # observability-only by default
            assert trainer.stats()["rollbacks_total"] == 0
        finally:
            trainer.stop()


class TestApi:
    def test_api_online_endpoint(self, flight, tmp_path):
        from deeplearning4j_tpu.ui.server import UIServer

        store = CheckpointStore(str(tmp_path / "ckpt"),
                                registry=MetricsRegistry())
        trainer, source, put, _ = _make(name="t-api", checkpoint_store=store,
                                        checkpoint_every_steps=4)
        server = UIServer.get_instance(port=0)
        trainer.start()
        try:
            put(source, 64)
            assert _wait(lambda: trainer.stats()["steps_total"] >= 4)
            url = f"http://127.0.0.1:{server.port}/api/online"
            body = json.loads(urllib.request.urlopen(url, timeout=10).read())
            t = body["trainers"]["t-api"]
            assert t["records_total"] >= 64
            assert t["checkpoints"]["versions"], t["checkpoints"]
            assert t["alive"] is True
        finally:
            trainer.stop()
            server.stop()


@pytest.mark.slow
class TestChaosSoak:
    def test_chaos_soak_feedforward(self, tmp_path):
        import sys

        sys.path.insert(0, "scripts")
        from chaos_soak import run_soak

        summary = run_soak(records=2048, nan_bursts=2, deadline_s=240,
                           flight_dir=str(tmp_path / "flight"))
        assert summary["alive"]
        assert summary["rollbacks"] >= 1
        assert summary["flight_bundles"]
        assert summary["warm_compiles"] == 0

    def test_chaos_soak_ragged_sequences(self, tmp_path):
        import sys

        sys.path.insert(0, "scripts")
        from chaos_soak import run_soak

        summary = run_soak(records=768, nan_bursts=1, seq=True,
                           deadline_s=300,
                           flight_dir=str(tmp_path / "flight"))
        assert summary["alive"] and summary["warm_compiles"] == 0
