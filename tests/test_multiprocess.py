"""Multi-PROCESS distributed training tests (VERDICT round-2 task 4).

The reference proves its cluster tier with `local[n]` SparkContext tests
(dl4j-spark/src/test/.../BaseSparkTest.java:90): multi-worker semantics in one
JVM. SURVEY.md §4.3 prescribes the jax.distributed analog — and goes further:
these tests spawn REAL OS processes that ``jax.distributed.initialize`` into
one CPU-backend cluster (2 processes x 2 virtual devices = one 4-device global
mesh, collectives over Gloo), run the parameter-averaging TrainingMaster
across the process boundary, and assert the result matches a single-process
run of the identical configuration bit-for-bit (same data order, same seeds;
only the all-reduce reduction order may differ -> tight allclose).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.utils.subproc import forced_cpu_env as _worker_env
from deeplearning4j_tpu.utils.subproc import free_port as _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "helpers", "multiproc_worker.py")


_MULTIPROC_SUPPORT = None


def _multiprocess_cpu_supported() -> bool:
    """Probe once whether this jaxlib can run cross-process computations on
    the CPU backend (older builds raise INVALID_ARGUMENT 'Multiprocess
    computations aren't implemented on the CPU backend'). A 2-process psum
    is the smallest computation that crosses the boundary."""
    global _MULTIPROC_SUPPORT
    if _MULTIPROC_SUPPORT is not None:
        return _MULTIPROC_SUPPORT
    port = _free_port()
    code = (
        "import sys, jax, jax.numpy as jnp\n"
        f"jax.distributed.initialize('127.0.0.1:{port}', 2, int(sys.argv[1]))\n"
        "out = jax.pmap(lambda x: jax.lax.psum(x, 'i'), axis_name='i')("
        "jnp.ones((jax.local_device_count(),)))\n"
        "assert float(out[0]) == jax.device_count()\n"
        "print('PROBE_OK')\n"
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code, str(i)], env=_worker_env(1),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO,
        )
        for i in range(2)
    ]
    ok = True
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            ok = ok and p.returncode == 0 and "PROBE_OK" in out
    except subprocess.TimeoutExpired:
        ok = False
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    _MULTIPROC_SUPPORT = ok
    return ok


@pytest.fixture(autouse=True)
def _require_multiprocess_cpu():
    if not _multiprocess_cpu_supported():
        pytest.skip(
            "jaxlib CPU backend lacks multiprocess computations here "
            "(probe psum failed)"
        )


def _run_cluster(mode: str, num_processes: int, out_dir: str,
                 local_devices: int = 2, timeout: float = 300.0,
                 extra=()):
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER,
             "--process-id", str(i), "--num-processes", str(num_processes),
             "--port", str(port), "--out", out_dir, "--mode", mode,
             "--local-devices", str(local_devices), *extra],
            env=_worker_env(local_devices),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO,
        )
        for i in range(num_processes)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
        assert "WORKER_OK" in out
    return outs


def _load(out_dir: str, mode: str, n: int):
    params = dict(np.load(os.path.join(out_dir, f"params_{mode}_{n}p.npz")))
    with open(os.path.join(out_dir, f"meta_{mode}_{n}p.json")) as f:
        meta = json.load(f)
    return params, meta


@pytest.mark.parametrize("mode", ["periodic", "sync"])
def test_two_processes_match_single_process(mode, tmp_path):
    """2 OS processes forming one 4-device mesh == 1 process with 4 devices."""
    out = str(tmp_path)
    _run_cluster(mode, num_processes=2, out_dir=out, local_devices=2)
    _run_cluster(mode, num_processes=1, out_dir=out, local_devices=4)

    mp_params, mp_meta = _load(out, mode, 2)
    sp_params, sp_meta = _load(out, mode, 1)

    assert mp_meta["process_count"] == 2
    assert sp_meta["process_count"] == 1
    assert mp_meta["devices"] == sp_meta["devices"] == 4

    assert set(mp_params) == set(sp_params)
    for k in sp_params:
        np.testing.assert_allclose(
            mp_params[k], sp_params[k], rtol=1e-5, atol=1e-6,
            err_msg=f"param {k} diverged between 2-process and 1-process runs",
        )
    assert mp_meta["loss"] == pytest.approx(sp_meta["loss"], rel=1e-4)
    # training actually moved: params differ from a fresh init
    assert any(np.abs(v).sum() > 0 for v in mp_params.values())


def test_per_host_input_pipeline_matches_broadcast(tmp_path):
    """SURVEY §7 hard part (d): each process loads ONLY its shard of every
    global batch (make_array_from_process_local_data) and training matches
    the broadcast pattern bit-for-bit-close."""
    out = str(tmp_path)
    _run_cluster("sync_localdata", num_processes=2, out_dir=out, local_devices=2)
    _run_cluster("sync", num_processes=2, out_dir=out, local_devices=2)

    local_params, local_meta = _load(out, "sync_localdata", 2)
    bcast_params, bcast_meta = _load(out, "sync", 2)
    assert local_meta["process_count"] == bcast_meta["process_count"] == 2
    assert set(local_params) == set(bcast_params)
    for k in bcast_params:
        np.testing.assert_allclose(
            local_params[k], bcast_params[k], rtol=1e-5, atol=1e-6,
            err_msg=f"param {k}: per-host pipeline diverged from broadcast")


def test_three_processes_match_single_process(tmp_path):
    """Scale the matrix past minimal-viable: 3 OS processes x 2 devices form
    one 6-device Gloo mesh (non-power-of-2) and match 1 process x 6 devices."""
    out = str(tmp_path)
    _run_cluster("sync", num_processes=3, out_dir=out, local_devices=2)
    _run_cluster("sync", num_processes=1, out_dir=out, local_devices=6)

    mp_params, mp_meta = _load(out, "sync", 3)
    sp_params, sp_meta = _load(out, "sync", 1)
    assert mp_meta["process_count"] == 3
    assert mp_meta["devices"] == sp_meta["devices"] == 6
    assert set(mp_params) == set(sp_params)
    for k in sp_params:
        np.testing.assert_allclose(
            mp_params[k], sp_params[k], rtol=1e-5, atol=1e-6,
            err_msg=f"param {k} diverged between 3-process and 1-process runs")


def test_dp_tp_across_process_boundary(tmp_path):
    """dp x tp where the 'model' axis spans BOTH processes' devices: the
    GSPMD tensor-parallel collectives cross the process boundary and the
    result matches the same (2,2) mesh inside one process."""
    out = str(tmp_path)
    _run_cluster("dp_tp", num_processes=2, out_dir=out, local_devices=2)
    _run_cluster("dp_tp", num_processes=1, out_dir=out, local_devices=4)

    mp_params, mp_meta = _load(out, "dp_tp", 2)
    sp_params, sp_meta = _load(out, "dp_tp", 1)
    assert mp_meta["process_count"] == 2
    assert mp_meta["devices"] == sp_meta["devices"] == 4
    assert set(mp_params) == set(sp_params)
    for k in sp_params:
        np.testing.assert_allclose(
            mp_params[k], sp_params[k], rtol=1e-5, atol=1e-6,
            err_msg=f"param {k} diverged between 2-process and 1-process dp x tp")


def test_worker_death_checkpoint_restart_matches_uninterrupted(tmp_path):
    """The recovery story (SURVEY §5.3 — 'can exceed the reference cheaply'):
    one of 2 workers dies mid-training (os._exit after round 2, the
    simulated kill -9); the survivor wedges in the next collective and the
    driver tears the job down; a FRESH cluster restores the checkpoint
    triple (adam state included) and finishes — final params match the
    uninterrupted run to all-reduce tolerance."""
    import time as _time

    out_a = str(tmp_path / "a"); os.makedirs(out_a)
    out_c = str(tmp_path / "c"); os.makedirs(out_c)
    ckpt = str(tmp_path / "recovery_ckpt")
    rounds = ["--rounds", "6"]

    # A: uninterrupted 6 rounds
    _run_cluster("recovery", num_processes=2, out_dir=out_a, extra=rounds)

    # B: rank 1 dies after round 2's checkpoint; survivor gets torn down
    port = _free_port()
    common = [sys.executable, WORKER, "--num-processes", "2",
              "--port", str(port), "--out", str(tmp_path), "--mode", "recovery",
              "--local-devices", "2", *rounds, "--ckpt", ckpt]
    survivor = subprocess.Popen(common + ["--process-id", "0"],
                                env=_worker_env(2), stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True, cwd=REPO)
    crasher = subprocess.Popen(common + ["--process-id", "1",
                                         "--crash-rank", "1",
                                         "--crash-after-round", "2"],
                               env=_worker_env(2), stdout=subprocess.PIPE,
                               stderr=subprocess.STDOUT, text=True, cwd=REPO)
    crash_out, _ = crasher.communicate(timeout=300)
    assert crasher.returncode == 17, crash_out[-3000:]
    assert "WORKER_CRASH pid=1 round=2" in crash_out
    # wait for round 2's (atomically-replaced) checkpoint, then tear the
    # survivor down like a failure detector would (it cannot make progress)
    ckpt_r2 = f"{ckpt}.r2.zip"
    deadline = _time.time() + 60
    while not os.path.exists(ckpt_r2) and _time.time() < deadline:
        _time.sleep(0.2)
    assert os.path.exists(ckpt_r2), "no round-2 checkpoint before the crash"
    survivor.kill()
    survivor.wait()

    # C: fresh cluster restores the triple and trains rounds 3..5
    _run_cluster("recovery", num_processes=2, out_dir=out_c,
                 extra=[*rounds, "--start-round", "3",
                        "--resume-from", ckpt_r2, "--tag", "resumed"])

    a_params, a_meta = _load(out_a, "recovery", 2)
    c_params, c_meta = _load(out_c, "recoveryresumed", 2)
    assert a_meta["process_count"] == c_meta["process_count"] == 2
    assert set(a_params) == set(c_params)
    for k in a_params:
        np.testing.assert_allclose(
            c_params[k], a_params[k], rtol=1e-5, atol=1e-6,
            err_msg=f"param {k}: restarted run diverged from uninterrupted")
