"""Multi-PROCESS distributed training tests (VERDICT round-2 task 4).

The reference proves its cluster tier with `local[n]` SparkContext tests
(dl4j-spark/src/test/.../BaseSparkTest.java:90): multi-worker semantics in one
JVM. SURVEY.md §4.3 prescribes the jax.distributed analog — and goes further:
these tests spawn REAL OS processes that ``jax.distributed.initialize`` into
one CPU-backend cluster (2 processes x 2 virtual devices = one 4-device global
mesh, collectives over Gloo), run the parameter-averaging TrainingMaster
across the process boundary, and assert the result matches a single-process
run of the identical configuration bit-for-bit (same data order, same seeds;
only the all-reduce reduction order may differ -> tight allclose).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "helpers", "multiproc_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(local_devices: int) -> dict:
    env = dict(os.environ)
    # Same recipe as conftest's _force_cpu_mesh, but via env because each
    # worker is a fresh interpreter: never let the axon TPU plugin register.
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={local_devices}"
    env.pop("JAX_NUM_PROCESSES", None)
    return env


def _run_cluster(mode: str, num_processes: int, out_dir: str,
                 local_devices: int = 2, timeout: float = 300.0):
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER,
             "--process-id", str(i), "--num-processes", str(num_processes),
             "--port", str(port), "--out", out_dir, "--mode", mode,
             "--local-devices", str(local_devices)],
            env=_worker_env(local_devices),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO,
        )
        for i in range(num_processes)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
        assert "WORKER_OK" in out
    return outs


def _load(out_dir: str, mode: str, n: int):
    params = dict(np.load(os.path.join(out_dir, f"params_{mode}_{n}p.npz")))
    with open(os.path.join(out_dir, f"meta_{mode}_{n}p.json")) as f:
        meta = json.load(f)
    return params, meta


@pytest.mark.parametrize("mode", ["periodic", "sync"])
def test_two_processes_match_single_process(mode, tmp_path):
    """2 OS processes forming one 4-device mesh == 1 process with 4 devices."""
    out = str(tmp_path)
    _run_cluster(mode, num_processes=2, out_dir=out, local_devices=2)
    _run_cluster(mode, num_processes=1, out_dir=out, local_devices=4)

    mp_params, mp_meta = _load(out, mode, 2)
    sp_params, sp_meta = _load(out, mode, 1)

    assert mp_meta["process_count"] == 2
    assert sp_meta["process_count"] == 1
    assert mp_meta["devices"] == sp_meta["devices"] == 4

    assert set(mp_params) == set(sp_params)
    for k in sp_params:
        np.testing.assert_allclose(
            mp_params[k], sp_params[k], rtol=1e-5, atol=1e-6,
            err_msg=f"param {k} diverged between 2-process and 1-process runs",
        )
    assert mp_meta["loss"] == pytest.approx(sp_meta["loss"], rel=1e-4)
    # training actually moved: params differ from a fresh init
    assert any(np.abs(v).sum() > 0 for v in mp_params.values())


def test_per_host_input_pipeline_matches_broadcast(tmp_path):
    """SURVEY §7 hard part (d): each process loads ONLY its shard of every
    global batch (make_array_from_process_local_data) and training matches
    the broadcast pattern bit-for-bit-close."""
    out = str(tmp_path)
    _run_cluster("sync_localdata", num_processes=2, out_dir=out, local_devices=2)
    _run_cluster("sync", num_processes=2, out_dir=out, local_devices=2)

    local_params, local_meta = _load(out, "sync_localdata", 2)
    bcast_params, bcast_meta = _load(out, "sync", 2)
    assert local_meta["process_count"] == bcast_meta["process_count"] == 2
    assert set(local_params) == set(bcast_params)
    for k in bcast_params:
        np.testing.assert_allclose(
            local_params[k], bcast_params[k], rtol=1e-5, atol=1e-6,
            err_msg=f"param {k}: per-host pipeline diverged from broadcast")
