"""NLP stack tests (reference suites: Word2VecTests, ParagraphVectorsTest,
GloveTest, tokenizer/vocab tests — deeplearning4j-nlp src/test)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BasicLineIterator,
    CollectionSentenceIterator,
    CommonPreprocessor,
    DefaultTokenizerFactory,
    Glove,
    Huffman,
    LabelledDocument,
    NGramTokenizerFactory,
    ParagraphVectors,
    Sequence,
    SequenceVectors,
    VocabCache,
    VocabConstructor,
    VocabWord,
    Word2Vec,
    load_txt_vectors,
    read_binary_model,
    read_sequence_vectors,
    write_binary_model,
    write_sequence_vectors,
    write_word_vectors,
)


def _corpus(n_repeat=40):
    """Toy corpus with strong structure: day names co-occur, color names
    co-occur — embeddings must separate the clusters."""
    sents = [
        "monday tuesday wednesday thursday friday",
        "tuesday monday thursday friday wednesday",
        "red green blue yellow purple",
        "green red yellow blue purple",
        "monday wednesday friday tuesday thursday",
        "blue purple red green yellow",
    ]
    return sents * n_repeat


class TestTokenization:
    def test_default_tokenizer(self):
        toks = DefaultTokenizerFactory().create("Hello World foo").get_tokens()
        assert toks == ["Hello", "World", "foo"]

    def test_common_preprocessor(self):
        tf = DefaultTokenizerFactory()
        tf.set_token_pre_processor(CommonPreprocessor())
        assert tf.create("Hello, World!  123").get_tokens() == ["hello", "world"]

    def test_ngram(self):
        tf = NGramTokenizerFactory(min_n=1, max_n=2)
        toks = tf.create("a b c").get_tokens()
        assert toks == ["a", "b", "c", "a b", "b c"]


class TestSentenceIterators:
    def test_collection_iterator(self):
        it = CollectionSentenceIterator(["one", "two"])
        assert list(it) == ["one", "two"]
        assert list(it) == ["one", "two"]  # reset works

    def test_line_iterator(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text("line one\nline two\nline three\n")
        it = BasicLineIterator(str(p))
        assert list(it) == ["line one", "line two", "line three"]


class TestVocabAndHuffman:
    def test_vocab_constructor_min_freq(self):
        seqs = [["a", "a", "a", "b", "b", "c"]]
        cache = VocabConstructor(min_word_frequency=2).build_vocab(seqs)
        assert cache.contains_word("a") and cache.contains_word("b")
        assert not cache.contains_word("c")
        assert cache.word_frequency("a") == 3
        assert cache.index_of("a") == 0  # frequency-sorted

    def test_huffman_codes(self):
        words = [VocabWord(w, c) for w, c in
                 [("the", 100), ("of", 60), ("and", 40), ("cat", 10), ("dog", 5)]]
        for i, w in enumerate(words):
            w.index = i
        Huffman(words).build()
        # prefix-free: no code is a prefix of another
        codes = ["".join(map(str, w.codes)) for w in words]
        for i, a in enumerate(codes):
            for j, b in enumerate(codes):
                if i != j:
                    assert not b.startswith(a)
        # frequent words get shorter codes
        assert len(words[0].codes) <= len(words[-1].codes)
        # points index inner nodes < n-1
        for w in words:
            assert all(0 <= p < len(words) - 1 for p in w.points)
            assert len(w.points) == len(w.codes)


class TestSequenceVectors:
    @pytest.mark.parametrize("mode", ["hs", "neg"])
    def test_skipgram_clusters(self, mode):
        vec = SequenceVectors(
            layer_size=24, window=3, epochs=8, seed=1, batch_size=256,
            learning_rate=0.05,
            use_hs=(mode == "hs"), negative=0 if mode == "hs" else 5,
        )
        seqs = [s.split() for s in _corpus()]
        vec.fit(seqs)
        # within-cluster similarity beats across-cluster
        same = vec.similarity("monday", "tuesday")
        cross = vec.similarity("monday", "red")
        assert same > cross, (same, cross)
        nearest = vec.words_nearest("monday", top_n=4)
        day_hits = sum(w in {"tuesday", "wednesday", "thursday", "friday"} for w in nearest)
        assert day_hits >= 3, nearest

    def test_cbow(self):
        vec = SequenceVectors(
            layer_size=24, window=3, epochs=10, seed=1, batch_size=128,
            elements_algo="cbow", use_hs=True, learning_rate=0.05,
        )
        vec.fit([s.split() for s in _corpus()])
        assert vec.similarity("red", "green") > vec.similarity("red", "monday")


class TestWord2Vec:
    def test_fit_sentences_and_queries(self):
        w2v = Word2Vec(layer_size=24, window=3, epochs=8, seed=1,
                       negative=5, use_hs=False, batch_size=256,
                       learning_rate=0.05, min_word_frequency=2)
        w2v.fit_sentences(_corpus())
        assert w2v.has_word("monday")
        v = w2v.get_word_vector("monday")
        assert v.shape == (24,)
        assert w2v.similarity("monday", "monday") == pytest.approx(1.0, abs=1e-5)
        assert w2v.similarity("blue", "yellow") > w2v.similarity("blue", "friday")

    def test_stop_words(self):
        w2v = Word2Vec(layer_size=8, epochs=1, stop_words={"the"})
        w2v.fit_sentences(["the cat sat the mat down here now"] * 5)
        assert not w2v.has_word("the")
        assert w2v.has_word("cat")


class TestParagraphVectors:
    def test_dbow_label_prediction(self):
        docs = []
        for i in range(30):
            docs.append(LabelledDocument(
                "monday tuesday wednesday thursday friday", ["DAYS"]))
            docs.append(LabelledDocument("red green blue yellow purple", ["COLORS"]))
        pv = ParagraphVectors(layer_size=24, window=3, epochs=6, seed=1,
                              use_hs=True, sequence_algo="dbow", batch_size=256,
                              learning_rate=0.05)
        pv.fit_documents(docs)
        assert pv.get_label_vector("DAYS") is not None
        assert pv.predict("wednesday friday monday") == "DAYS"
        assert pv.predict("green purple blue") == "COLORS"

    def test_dm_runs(self):
        docs = [LabelledDocument("a b c d e", ["L1"]),
                LabelledDocument("f g h i j", ["L2"])] * 10
        pv = ParagraphVectors(layer_size=8, window=2, epochs=2, seed=1,
                              sequence_algo="dm", use_hs=True, batch_size=64)
        pv.fit_documents(docs)
        assert pv.get_label_vector("L1").shape == (8,)

    def test_infer_vector_near_label(self):
        docs = [LabelledDocument("monday tuesday wednesday thursday friday", ["DAYS"]),
                LabelledDocument("red green blue yellow purple", ["COLORS"])] * 20
        pv = ParagraphVectors(layer_size=16, window=3, epochs=6, seed=1,
                              use_hs=True, sequence_algo="dbow", batch_size=128,
                              learning_rate=0.05)
        pv.fit_documents(docs)
        assert pv.similarity_to_label("tuesday thursday", "DAYS") > \
            pv.similarity_to_label("tuesday thursday", "COLORS")


class TestGlove:
    def test_glove_clusters(self):
        glove = Glove(layer_size=16, window=4, epochs=40, seed=1,
                      learning_rate=0.05, batch_size=512)
        glove.fit(_corpus())
        assert glove.similarity("monday", "tuesday") > glove.similarity("monday", "blue")
        assert glove.get_word_vector("red").shape == (16,)


class TestSerialization:
    def _small_model(self):
        vec = SequenceVectors(layer_size=8, window=2, epochs=2, seed=1,
                              use_hs=True, negative=0, batch_size=64)
        vec.fit([s.split() for s in _corpus(5)])
        return vec

    def test_c_text_roundtrip(self, tmp_path):
        vec = self._small_model()
        path = str(tmp_path / "vecs.txt")
        write_word_vectors(vec.lookup, path)
        loaded = load_txt_vectors(path)
        assert loaded.vocab.num_words() == vec.vocab.num_words()
        np.testing.assert_allclose(
            loaded.vector("monday"), vec.get_word_vector("monday"), atol=1e-5
        )

    def test_c_binary_roundtrip(self, tmp_path):
        vec = self._small_model()
        path = str(tmp_path / "vecs.bin")
        write_binary_model(vec.lookup, path)
        loaded = read_binary_model(path)
        np.testing.assert_allclose(
            loaded.vector("red"), vec.get_word_vector("red"), atol=1e-6
        )

    def test_zip_roundtrip_resumable(self, tmp_path):
        vec = self._small_model()
        path = str(tmp_path / "model.zip")
        write_sequence_vectors(vec, path)
        loaded = read_sequence_vectors(path)
        np.testing.assert_array_equal(loaded.lookup.syn0, vec.lookup.syn0)
        np.testing.assert_array_equal(loaded.lookup.syn1, vec.lookup.syn1)
        # training can continue on the restored model
        loaded.fit([s.split() for s in _corpus(2)])
        assert loaded.similarity("monday", "tuesday") is not None
