"""Cross-framework golden-value tests against PyTorch (CPU).

The reference proved layer semantics against ND4J's independently-implemented
kernels; the analog here is an independent framework: identical weights are
loaded into torch modules and outputs compared elementwise. This pins the
semantics gradcheck can't see — padding arithmetic, layout conventions,
normalization epsilon/averaging, loss reductions — to an external
implementation rather than to our own math.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from deeplearning4j_tpu import (  # noqa: E402
    DenseLayer,
    InputType,
)
from deeplearning4j_tpu.nn.layers.convolution import ConvolutionLayer  # noqa: E402
from deeplearning4j_tpu.nn.layers.pooling import SubsamplingLayer  # noqa: E402
from deeplearning4j_tpu.nn.layers.attention import LayerNormLayer  # noqa: E402
from deeplearning4j_tpu.nn.losses import get_loss  # noqa: E402


def _t(a):
    return torch.from_numpy(np.asarray(a, dtype=np.float32))


def _f32(tree):
    # conftest enables x64: init_params yields float64; cast for f32 parity
    return jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jnp.float32), tree)


class TestConvParity:
    @pytest.mark.parametrize("mode,stride", [("truncate", (1, 1)),
                                             ("truncate", (2, 2)),
                                             ("same", (1, 1)),
                                             ("same", (2, 2))])
    def test_conv2d_matches_torch(self, mode, stride):
        rng = np.random.default_rng(0)
        B, H, W, Cin, Cout, K = 2, 9, 11, 3, 5, 3
        layer = ConvolutionLayer(n_out=Cout, kernel=(K, K), stride=stride,
                                 convolution_mode=mode, activation="identity")
        params = _f32(layer.init_params(jax.random.PRNGKey(0),
                                        InputType.convolutional(H, W, Cin)))
        x = rng.normal(size=(B, H, W, Cin)).astype(np.float32)
        ours, _ = layer.apply(params, jnp.asarray(x), layer.init_state(
            InputType.convolutional(H, W, Cin)))

        w_hwio = np.asarray(params["W"], np.float32)  # [K,K,Cin,Cout]
        w_oihw = np.transpose(w_hwio, (3, 2, 0, 1))
        x_nchw = np.transpose(x, (0, 3, 1, 2))
        if mode == "same":
            # torch 'same' only supports stride 1; replicate XLA's asymmetric
            # SAME padding (low = total//2) with explicit F.pad
            out_h = -(-H // stride[0])
            out_w = -(-W // stride[1])
            pad_h = max((out_h - 1) * stride[0] + K - H, 0)
            pad_w = max((out_w - 1) * stride[1] + K - W, 0)
            xt = torch.nn.functional.pad(
                _t(x_nchw),
                (pad_w // 2, pad_w - pad_w // 2, pad_h // 2, pad_h - pad_h // 2))
            ref = torch.nn.functional.conv2d(
                xt, _t(w_oihw), _t(params["b"]), stride=stride)
        else:
            ref = torch.nn.functional.conv2d(
                _t(x_nchw), _t(w_oihw), _t(params["b"]), stride=stride)
        ref = ref.numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-4, atol=1e-5)

    def test_maxpool_matches_torch(self):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
        layer = SubsamplingLayer(pooling_type="max", kernel=(2, 2), stride=(2, 2))
        it = InputType.convolutional(8, 8, 3)
        ours, _ = layer.apply({}, jnp.asarray(x), layer.init_state(it))
        ref = torch.nn.functional.max_pool2d(
            _t(np.transpose(x, (0, 3, 1, 2))), 2, 2).numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-5, atol=1e-6)

    def test_avgpool_matches_torch(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
        layer = SubsamplingLayer(pooling_type="avg", kernel=(2, 2), stride=(2, 2))
        it = InputType.convolutional(8, 8, 3)
        ours, _ = layer.apply({}, jnp.asarray(x), layer.init_state(it))
        ref = torch.nn.functional.avg_pool2d(
            _t(np.transpose(x, (0, 3, 1, 2))), 2, 2).numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-5, atol=1e-6)


class TestDenseAndNormParity:
    def test_dense_relu_matches_torch(self):
        rng = np.random.default_rng(3)
        layer = DenseLayer(n_out=16, activation="relu")
        params = _f32(layer.init_params(jax.random.PRNGKey(1),
                                        InputType.feed_forward(8)))
        x = rng.normal(size=(4, 8)).astype(np.float32)
        ours, _ = layer.apply(params, jnp.asarray(x), {})
        ref = torch.relu(_t(x) @ _t(params["W"]) + _t(params["b"])).numpy()
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-5, atol=1e-6)

    def test_layernorm_matches_torch(self):
        rng = np.random.default_rng(4)
        layer = LayerNormLayer()
        # non-trivial gamma/beta so the affine part is exercised
        params = {"gamma": jnp.asarray(rng.normal(size=12), jnp.float32),
                  "beta": jnp.asarray(rng.normal(size=12), jnp.float32)}
        x = rng.normal(size=(5, 12)).astype(np.float32)
        ours, _ = layer.apply(params, jnp.asarray(x), {})
        ref = torch.nn.functional.layer_norm(
            _t(x), (12,), _t(params["gamma"]), _t(params["beta"]),
            eps=layer.eps).numpy()
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-4, atol=1e-5)


class TestLRNParity:
    def test_lrn_matches_torch(self):
        """Cross-channel LRN vs torch.nn.LocalResponseNorm. The conventions
        differ: the reference (and this repo) uses denominator
        (k + alpha * sum)^beta while torch uses (k + alpha/size * sum)^beta —
        so torch gets alpha*n. Torch normalizes over the channel dim of
        [N,C,H,W]; ours is NHWC trailing-axis."""
        from deeplearning4j_tpu.nn.layers.normalization import (
            LocalResponseNormalization,
        )

        rng = np.random.default_rng(11)
        k, n, alpha, beta = 2.0, 5, 1e-3, 0.75
        layer = LocalResponseNormalization(k=k, n=n, alpha=alpha, beta=beta)
        x = rng.normal(size=(2, 6, 6, 16)).astype(np.float32)
        ours, _ = layer.apply({}, jnp.asarray(x), {})
        t_lrn = torch.nn.LocalResponseNorm(size=n, alpha=alpha * n, beta=beta, k=k)
        ref = t_lrn(_t(x).permute(0, 3, 1, 2)).permute(0, 2, 3, 1).numpy()
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-5, atol=1e-6)

    def test_embedding_matches_torch(self):
        from deeplearning4j_tpu.nn.layers.dense import EmbeddingLayer

        rng = np.random.default_rng(12)
        layer = EmbeddingLayer(n_in=20, n_out=8, activation="identity",
                               has_bias=False)
        params = _f32(layer.init_params(jax.random.PRNGKey(2),
                                        InputType.feed_forward(20)))
        idx = rng.integers(0, 20, size=(7, 1))
        ours, _ = layer.apply(params, jnp.asarray(idx), {})
        emb = torch.nn.Embedding(20, 8)
        with torch.no_grad():
            emb.weight.copy_(_t(params["W"]))
        ref = emb(torch.from_numpy(idx[:, 0])).detach().numpy()
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-6, atol=1e-7)


class TestLossParity:
    def test_mcxent_matches_torch_cross_entropy(self):
        rng = np.random.default_rng(5)
        logits = rng.normal(size=(6, 4)).astype(np.float32)
        y_idx = rng.integers(0, 4, 6)
        y = np.eye(4, dtype=np.float32)[y_idx]
        # mcxent is softmax-fused: it takes PRE-activations (logits)
        ours = float(get_loss("mcxent")(jnp.asarray(y), jnp.asarray(logits)))
        ref = float(torch.nn.functional.cross_entropy(
            _t(logits), torch.from_numpy(y_idx)))
        np.testing.assert_allclose(ours, ref, rtol=1e-5)

    def test_mse_matches_torch(self):
        rng = np.random.default_rng(6)
        pred = rng.normal(size=(6, 3)).astype(np.float32)
        y = rng.normal(size=(6, 3)).astype(np.float32)
        ours = float(get_loss("mse")(jnp.asarray(y), jnp.asarray(pred)))
        ref = float(torch.nn.functional.mse_loss(_t(pred), _t(y)))
        # reference MSE conventions differ by per-row vs per-element mean at
        # most a constant factor; accept either normalization
        assert ours == pytest.approx(ref, rel=1e-5) or \
            ours == pytest.approx(ref * y.shape[1], rel=1e-5)


class TestBatchNormParity:
    def test_train_and_eval_match_torch(self):
        from deeplearning4j_tpu.nn.layers.normalization import BatchNormalization

        rng = np.random.default_rng(7)
        C = 5
        layer = BatchNormalization()
        it = InputType.convolutional(6, 7, C)
        params = {"gamma": jnp.asarray(rng.normal(size=C) + 1, jnp.float32),
                  "beta": jnp.asarray(rng.normal(size=C), jnp.float32)}
        state = _f32(layer.init_state(it))
        x = rng.normal(size=(4, 6, 7, C)).astype(np.float32)

        tbn = torch.nn.BatchNorm2d(C, eps=layer.eps,
                                   momentum=1 - layer.decay)  # decay==1-momentum
        with torch.no_grad():
            tbn.weight.copy_(_t(params["gamma"]))
            tbn.bias.copy_(_t(params["beta"]))
        tbn.train()
        ref_train = tbn(_t(np.transpose(x, (0, 3, 1, 2)))).detach().numpy()
        ours_train, new_state = layer.apply(params, jnp.asarray(x), state,
                                            train=True)
        np.testing.assert_allclose(np.asarray(ours_train),
                                   ref_train.transpose(0, 2, 3, 1),
                                   rtol=1e-4, atol=1e-5)
        # running stats: torch tracks UNBIASED var in running_var while ours
        # follows the reference's biased convention — compare the mean and
        # the biased-corrected var
        n = x.size // C
        np.testing.assert_allclose(np.asarray(new_state["mean"]),
                                   tbn.running_mean.numpy(), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(new_state["var"]),
            # unbias-corrected torch running var back to biased: the batch
            # contribution was scaled by n/(n-1)
            (tbn.running_var.numpy() - layer.decay * 1.0) * (n - 1) / n
            + layer.decay * 1.0,
            rtol=1e-4, atol=1e-5)

        # eval mode from identical running stats
        tbn.eval()
        with torch.no_grad():
            tbn.running_mean.copy_(_t(new_state["mean"]))
            tbn.running_var.copy_(_t(new_state["var"]))
        ref_eval = tbn(_t(np.transpose(x, (0, 3, 1, 2)))).detach().numpy()
        ours_eval, _ = layer.apply(params, jnp.asarray(x), new_state,
                                   train=False)
        np.testing.assert_allclose(np.asarray(ours_eval),
                                   ref_eval.transpose(0, 2, 3, 1),
                                   rtol=1e-4, atol=1e-5)


class TestLSTMParity:
    def test_graves_lstm_matches_torch_lstm(self):
        """Zero peepholes reduce GravesLSTM to the standard LSTM; gate
        columns [a,f,o,i] (LSTMHelpers parity) remap to torch's (i,f,g,o)."""
        from deeplearning4j_tpu import GravesLSTM

        rng = np.random.default_rng(8)
        F, H, B, T = 6, 5, 3, 7
        layer = GravesLSTM(n_in=F, n_out=H, activation="tanh")
        it = InputType.recurrent(F, T)
        params = _f32(layer.init_params(jax.random.PRNGKey(4), it))
        params = dict(params)
        for k in ("pF", "pI", "pO"):
            params[k] = jnp.zeros_like(params[k])
        x = rng.normal(size=(B, T, F)).astype(np.float32)
        ours, _ = layer.apply(params, jnp.asarray(x), layer.init_state(it))

        W = np.asarray(params["W"])    # [F, 4H], columns [a, f, o, i]
        RW = np.asarray(params["RW"])  # [H, 4H]
        b = np.asarray(params["b"])    # [4H]

        def reorder(m):
            # ours [a, f, o, i] -> torch (i, f, g(a), o)
            a, f, o, i = (m[..., :H], m[..., H:2 * H],
                          m[..., 2 * H:3 * H], m[..., 3 * H:])
            return np.concatenate([i, f, a, o], axis=-1)

        tl = torch.nn.LSTM(F, H, batch_first=True)
        with torch.no_grad():
            tl.weight_ih_l0.copy_(_t(reorder(W).T))
            tl.weight_hh_l0.copy_(_t(reorder(RW).T))
            tl.bias_ih_l0.copy_(_t(reorder(b)))
            tl.bias_hh_l0.copy_(torch.zeros(4 * H))
        ref, _ = tl(_t(x))
        np.testing.assert_allclose(np.asarray(ours), ref.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
