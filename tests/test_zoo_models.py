"""AlexNet + GoogLeNet zoo configs: structure, JSON round-trip, and tiny
end-to-end training (model-zoo role parity — see models/alexnet.py,
models/googlenet.py docstrings)."""

import numpy as np

from deeplearning4j_tpu import (
    ComputationGraph,
    ComputationGraphConfiguration,
    MultiLayerConfiguration,
    MultiLayerNetwork,
)
from deeplearning4j_tpu.datasets.iterators import MultiDataSet
from deeplearning4j_tpu.models import alexnet_conf, googlenet_conf


class TestAlexNet:
    def test_structure_and_json(self):
        conf = alexnet_conf()
        # 5 convs, 2 LRNs, 3 pools, 3 dense/output
        kinds = [type(l).__name__ for l in conf.layers]
        assert kinds.count("ConvolutionLayer") == 5
        assert kinds.count("LocalResponseNormalization") == 2
        assert kinds.count("SubsamplingLayer") == 3
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        assert conf2.to_dict() == conf.to_dict()

    def test_too_small_input_raises(self):
        """32x32 collapses to a 0-size spatial dim at the last pool; the
        framework must refuse loudly (a silent empty tensor trains a dead
        network whose loss freezes at ln(n_classes) — regression)."""
        import pytest

        with pytest.raises(ValueError, match="output size"):
            alexnet_conf(height=32, width=32, n_classes=4).layer_input_types()

    def test_tiny_trains(self, rng):
        conf = alexnet_conf(height=64, width=64, n_classes=4, dropout=0.0,
                            updater="adam", learning_rate=1e-3)
        net = MultiLayerNetwork(conf).init()
        x = rng.normal(size=(8, 64, 64, 3))
        y = np.eye(4)[rng.integers(0, 4, size=8)]
        first = net.loss_fn(net.params, x, y, train=False)
        net.fit((x, y), epochs=8)
        assert np.isfinite(net.score())
        assert net.score() < float(first)
        out = net.output(x)
        assert out.shape == (8, 4)


class TestGoogLeNet:
    def test_structure_and_json(self):
        conf = googlenet_conf()
        # 9 inception modules, each a 4-way MergeVertex concat
        merges = [n for n, v in conf.vertices.items()
                  if type(v).__name__ == "MergeVertex"]
        assert len(merges) == 9
        assert all(len(conf.vertex_inputs[m]) == 4 for m in merges)
        out_t = conf.output_types()[0]
        assert out_t.size == 1000
        conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
        assert conf2.to_dict() == conf.to_dict()

    def test_aux_heads_multi_output(self):
        conf = googlenet_conf(n_classes=10, aux_heads=True)
        assert conf.network_outputs == ["out", "aux1", "aux2"]

    def test_transfer_learning_head_surgery(self, rng):
        """Zoo-scale transfer: freeze GoogLeNet through the last inception
        module, replace the classifier head for a new class count — the
        standard fine-tuning workflow on a real multi-branch graph."""
        from deeplearning4j_tpu import (OutputLayer, TransferLearning,
                                        UpdaterConfig)
        from deeplearning4j_tpu.nn.layers.frozen import FrozenLayer
        from deeplearning4j_tpu.nn.transferlearning import FineTuneConfiguration

        conf = googlenet_conf(height=64, width=64, n_classes=100, dropout=0.0,
                              updater="adam", learning_rate=1e-3)
        net = ComputationGraph(conf).init()
        stem_w_before = np.asarray(net.params["stem_conv1"]["W"])

        new_net = (
            TransferLearning.GraphBuilder(net)
            .fine_tune_configuration(FineTuneConfiguration(
                updater=UpdaterConfig(updater="adam", learning_rate=5e-3)))
            .set_feature_extractor("i5b")  # freezes everything upstream
            .remove_vertex_and_connections("out")
            .add_layer("new_out", OutputLayer(n_out=4, activation="softmax",
                                              loss="mcxent"), "drop")
            .set_outputs("new_out")
            .build()
        )
        assert isinstance(new_net.conf.vertices["stem_conv1"].layer, FrozenLayer)
        assert new_net.params["new_out"]["W"].shape == (1024, 4)

        x = rng.normal(size=(4, 64, 64, 3))
        y = np.eye(4)[rng.integers(0, 4, size=4)]
        new_net.fit((x, y), epochs=2)
        np.testing.assert_array_equal(
            np.asarray(new_net.params["stem_conv1"]["W"]), stem_w_before)
        assert new_net.output(x).shape == (4, 4)

    def test_tiny_trains_with_aux(self, rng):
        """GoogLeNet with aux heads: multi-output losses sum and the graph
        trains end to end."""
        # 112x112 is the smallest canonical-ish size where the aux heads'
        # avgpool(5,stride 3) still sees >=5x5 at stage 4 (the output-size
        # validator rejects smaller inputs loudly)
        conf = googlenet_conf(height=112, width=112, n_classes=4, dropout=0.0,
                              aux_heads=True, updater="adam",
                              learning_rate=1e-3)
        net = ComputationGraph(conf).init()
        x = rng.normal(size=(4, 112, 112, 3))
        y = np.eye(4)[rng.integers(0, 4, size=4)]
        labels = [y, y, y]  # main + two aux heads share targets
        first = net.loss_fn(net.params, [x], labels, train=False)
        net.fit(MultiDataSet(features=[x], labels=labels), epochs=6)
        assert np.isfinite(net.score())
        assert net.score() < float(first)
        outs = net.output(x)
        assert len(outs) == 3 and outs[0].shape == (4, 4)


class TestDBN:
    def test_pretrain_then_finetune(self, rng):
        """The reference's founding workflow: greedy layerwise CD-k pretrain
        over the RBM stack, then supervised fine-tune."""
        from deeplearning4j_tpu.models import dbn_conf

        conf = dbn_conf(n_in=12, layer_sizes=(10, 6), n_classes=3,
                        visible_unit="gaussian", updater="adam",
                        learning_rate=5e-3)
        net = MultiLayerNetwork(conf).init()
        x = rng.normal(size=(64, 12)).astype(np.float32)
        w = rng.normal(size=(12, 3))
        y = np.eye(3, dtype=np.float32)[(x @ w).argmax(-1)]

        net.pretrain((x, y), epochs=3)  # unsupervised: labels unused
        first = float(net.loss_fn(net.params, x, y, train=False))
        net.fit((x, y), epochs=25)
        assert np.isfinite(net.score())
        assert net.score() < first
        assert net.output(x).shape == (64, 3)

    def test_structure_json(self):
        from deeplearning4j_tpu.models import dbn_conf

        conf = dbn_conf()
        kinds = [type(l).__name__ for l in conf.layers]
        assert kinds == ["RBM", "RBM", "RBM", "OutputLayer"]
        assert conf.layers[0].visible_unit == "binary"
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        assert conf2.to_dict() == conf.to_dict()
