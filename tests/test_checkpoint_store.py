"""CheckpointStore (ISSUE 10): versioned, atomic, retention-bounded model
checkpoints with bit-identical restore-and-resume — params, updater
moments, step count AND the training rng key — on both net classes,
including a bf16-storage MeshLayout model.

Bit-exactness note (memory: env quirks): resumed trajectories replay the
SAME program shapes, so the x64 suite's f64 reduction orders match exactly.
"""

import os
import zipfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (
    ComputationGraph,
    ComputationGraphConfiguration,
    DenseLayer,
    InputType,
    MultiLayerConfiguration,
    MultiLayerNetwork,
    OutputLayer,
    UpdaterConfig,
)
from deeplearning4j_tpu.runtime.checkpoint import CheckpointStore
from deeplearning4j_tpu.telemetry import MetricsRegistry


def _conf(seed=7, features=12, hidden=16, classes=3, params_dtype=None,
          dropout=0.0):
    return MultiLayerConfiguration(
        layers=[
            DenseLayer(n_out=hidden, activation="tanh", dropout=dropout),
            OutputLayer(n_out=classes, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.feed_forward(features),
        updater=UpdaterConfig(updater="adam", learning_rate=1e-2),
        seed=seed,
        params_dtype=params_dtype,
    )


def _graph_conf(seed=5, features=10, classes=3):
    return (ComputationGraphConfiguration.builder()
            .add_inputs("in")
            .add_layer("h", DenseLayer(n_out=12, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=classes, activation="softmax",
                                          loss="mcxent"), "h")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(features))
            .build())


def _windows(rng, n, batch=8, features=12, classes=3, k=2):
    xs = rng.normal(size=(n, k, batch, features)).astype(np.float32)
    ys = np.stack([
        np.eye(classes, dtype=np.float32)[rng.integers(0, classes,
                                                       (k, batch))]
        for _ in range(n)])
    return xs, ys


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestStoreMechanics:
    def test_versions_monotonic_and_atomic(self, tmp_path):
        net = MultiLayerNetwork(_conf()).init()
        store = CheckpointStore(str(tmp_path), retain=10,
                                registry=MetricsRegistry())
        infos = [store.save(net) for _ in range(3)]
        assert [i.version for i in infos] == [1, 2, 3]
        # no torn temp files survive a save
        assert all(not f.startswith(".tmp") for f in os.listdir(tmp_path))
        # a fresh store over the same directory resumes the id sequence
        store2 = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        assert store2.save(net).version == 4

    def test_retention_prunes_oldest_only(self, tmp_path):
        net = MultiLayerNetwork(_conf()).init()
        store = CheckpointStore(str(tmp_path), retain=2,
                                registry=MetricsRegistry())
        for _ in range(5):
            store.save(net)
        versions = [v.version for v in store.versions()]
        assert versions == [4, 5]
        assert store.latest().version == 5

    def test_torn_and_foreign_files_ignored(self, tmp_path):
        net = MultiLayerNetwork(_conf()).init()
        store = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        store.save(net)
        (tmp_path / "model-v00000099.zip").write_bytes(b"not a zip")
        (tmp_path / "notes.txt").write_text("hi")
        assert [v.version for v in store.versions()] == [1]
        # ...but the id scan still moves past the torn file's number
        assert store.save(net).version == 100

    def test_save_async_join_surfaces_errors(self, tmp_path):
        net = MultiLayerNetwork(_conf()).init()
        store = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        v = store.save_async(net)
        store.join()
        assert store.latest().version == v
        assert store.versions()[0].model_class == "MultiLayerNetwork"

    def test_restore_missing_version_raises(self, tmp_path):
        store = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        with pytest.raises(FileNotFoundError):
            store.restore()
        net = MultiLayerNetwork(_conf()).init()
        store.save(net)
        with pytest.raises(FileNotFoundError):
            store.restore(42)


class TestResumeBitIdentical:
    def _run(self, net, xs, ys):
        losses = []
        for i in range(xs.shape[0]):
            losses.append(net.fit_on_device(xs[i], ys[i]))
        return np.concatenate(losses)

    def test_mln_resume_matches_uninterrupted(self, tmp_path):
        rng = np.random.default_rng(0)
        xs, ys = _windows(rng, 6)
        ref = MultiLayerNetwork(_conf()).init()
        live = MultiLayerNetwork(_conf()).init()
        store = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        ref_losses = self._run(ref, xs, ys)
        self._run(live, xs[:3], ys[:3])
        store.save(live)
        resumed = store.restore()
        assert resumed.iteration == live.iteration
        _leaves_equal(resumed.params, live.params)
        _leaves_equal(resumed.opt_state, live.opt_state)
        np.testing.assert_array_equal(np.asarray(resumed._rng),
                                      np.asarray(live._rng))
        tail = self._run(resumed, xs[3:], ys[3:])
        np.testing.assert_array_equal(tail, ref_losses[len(ref_losses) // 2:])
        _leaves_equal(resumed.params, ref.params)

    def test_mln_resume_with_dropout_rng_chain(self, tmp_path):
        """Dropout draws come from the stored rng key: the resumed chain
        must replay the EXACT masks the uninterrupted run drew."""
        rng = np.random.default_rng(1)
        xs, ys = _windows(rng, 4)
        ref = MultiLayerNetwork(_conf(dropout=0.5)).init()
        live = MultiLayerNetwork(_conf(dropout=0.5)).init()
        store = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        ref_losses = self._run(ref, xs, ys)
        self._run(live, xs[:2], ys[:2])
        store.save(live)
        resumed = store.restore()
        tail = self._run(resumed, xs[2:], ys[2:])
        np.testing.assert_array_equal(tail, ref_losses[len(ref_losses) // 2:])
        _leaves_equal(resumed.params, ref.params)

    def test_graph_resume_matches_uninterrupted(self, tmp_path):
        rng = np.random.default_rng(2)
        xs, ys = _windows(rng, 6, features=10)
        ref = ComputationGraph(_graph_conf()).init()
        live = ComputationGraph(_graph_conf()).init()
        store = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        ref_losses = self._run(ref, xs, ys)
        self._run(live, xs[:3], ys[:3])
        store.save(live)
        resumed = store.restore()
        assert isinstance(resumed, ComputationGraph)
        assert resumed.iteration == live.iteration
        _leaves_equal(resumed.opt_state, live.opt_state)
        tail = self._run(resumed, xs[3:], ys[3:])
        np.testing.assert_array_equal(tail, ref_losses[len(ref_losses) // 2:])
        _leaves_equal(resumed.params, ref.params)

    def test_load_into_keeps_executables_warm(self, tmp_path):
        from deeplearning4j_tpu.runtime.compile_manager import (
            get_compile_manager,
        )

        rng = np.random.default_rng(3)
        xs, ys = _windows(rng, 3)
        net = MultiLayerNetwork(_conf()).init()
        store = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        net.fit_on_device(xs[0], ys[0])
        store.save(net)
        saved_params = jax.tree_util.tree_map(np.asarray, net.params)
        net.fit_on_device(xs[1], ys[1])
        cm = get_compile_manager()
        before = cm.compiles.value
        store.load_into(net)  # rollback in place
        _leaves_equal(net.params, saved_params)
        net.fit_on_device(xs[2], ys[2])  # same shapes: must be a cache hit
        assert cm.compiles.value - before == 0


class TestBf16MeshLayoutRoundtrip:
    def test_bf16_fsdp_model_roundtrips_bit_identical(self, tmp_path):
        from deeplearning4j_tpu.parallel import MeshLayout

        rng = np.random.default_rng(4)
        # hidden/features divisible by fsdp=4 so the kernels actually shard
        net = MultiLayerNetwork(_conf(features=16, hidden=32,
                                      classes=4)).init()
        lo = MeshLayout(data=1, fsdp=4, params_dtype="bfloat16",
                        devices=jax.devices()[:4])
        lo.apply(net)
        xs = rng.normal(size=(2, 8, 16)).astype(np.float32)
        ys = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (2, 8))]
        net.fit_on_device(xs, ys)
        store = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        store.save(net)

        # fresh-model restore: conf round-trips params_dtype, leaves come
        # back bf16 and bit-identical (bf16 -> f32 widening is lossless)
        restored = store.restore()
        for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                        jax.tree_util.tree_leaves(net.params)):
            assert a.dtype == jnp.bfloat16
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), np.asarray(b, np.float32))

        # in-place rollback re-places leaves on the net's layout
        net.fit_on_device(xs, ys)
        store.load_into(net)
        W = net.params[0]["W"]
        assert W.dtype == jnp.bfloat16
        assert "fsdp" in str(W.sharding.spec)
        for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                        jax.tree_util.tree_leaves(net.params)):
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), np.asarray(b, np.float32))
        # and the restored model still trains sharded to a finite loss
        losses = net.fit_on_device(xs, ys)
        assert np.all(np.isfinite(losses))


def test_rng_entry_present_in_container(tmp_path):
    net = MultiLayerNetwork(_conf()).init()
    store = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
    info = store.save(net)
    with zipfile.ZipFile(info.path) as zf:
        names = set(zf.namelist())
    assert {"configuration.json", "coefficients.npz", "updaterState.npz",
            "state.npz", "meta.json", "rng.npz", "manifest.json"} <= names


class TestIntegrityQuarantine:
    """ISSUE 14: sha256 manifest verification, quarantine, and fallback to
    the previous good version on every corruption shape a killed/ill
    writer can leave behind."""

    def _seed(self, tmp_path, n=2):
        net = MultiLayerNetwork(_conf()).init()
        store = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        infos = [store.save(net) for _ in range(n)]
        return net, store, infos

    def test_verify_ok_and_legacy(self, tmp_path):
        net, store, (i1, i2) = self._seed(tmp_path)
        assert store.verify(1) == "ok"
        # a manifest-less container (pre-manifest era) is accepted as-is
        with zipfile.ZipFile(i2.path) as zf:
            entries = {n: zf.read(n) for n in zf.namelist()
                       if n != "manifest.json"}
        with zipfile.ZipFile(i2.path, "w") as zf:
            for name, data in entries.items():
                zf.writestr(name, data)
        assert store.verify(2) == "legacy"

    def test_truncated_zip_quarantined_with_fallback(self, tmp_path):
        from deeplearning4j_tpu.testing.chaos import truncate_file

        net, store, (i1, i2) = self._seed(tmp_path)
        truncate_file(i2.path, keep_frac=0.4)
        model, info = store.restore_with_info()
        assert info.version == 1
        assert os.path.exists(i2.path + ".quarantine")
        assert [v.version for v in store.versions()] == [1]
        assert store._m_corrupt.value >= 1

    def test_bad_rng_entry_digest_mismatch(self, tmp_path):
        from deeplearning4j_tpu.runtime.checkpoint import (
            CheckpointCorruptError,
        )

        net, store, (i1, i2) = self._seed(tmp_path)
        # rewrite rng.npz in place; the manifest still carries the old
        # digest, so the zip stays structurally valid but fails verify
        with zipfile.ZipFile(i2.path) as zf:
            entries = {n: zf.read(n) for n in zf.namelist()}
        entries["rng.npz"] = b"\x00" * 32
        with zipfile.ZipFile(i2.path, "w") as zf:
            for name, data in entries.items():
                zf.writestr(name, data)
        with pytest.raises(CheckpointCorruptError, match="rng.npz"):
            store.verify(2)
        model, info = store.restore_with_info()
        assert info.version == 1
        assert os.path.exists(i2.path + ".quarantine")

    def test_manifest_zip_mismatch_quarantined(self, tmp_path):
        from deeplearning4j_tpu.runtime.checkpoint import (
            CheckpointCorruptError,
        )

        net, store, (i1, i2) = self._seed(tmp_path)
        with zipfile.ZipFile(i2.path, "a") as zf:
            zf.writestr("smuggled.bin", b"x")
        with pytest.raises(CheckpointCorruptError, match="mismatch"):
            store.verify(2)
        model, info = store.restore_with_info()
        assert info.version == 1
        assert os.path.exists(i2.path + ".quarantine")

    def test_pinned_corrupt_version_raises_after_quarantine(self, tmp_path):
        from deeplearning4j_tpu.runtime.checkpoint import (
            CheckpointCorruptError,
        )
        from deeplearning4j_tpu.testing.chaos import truncate_file

        net, store, (i1, i2) = self._seed(tmp_path)
        truncate_file(i2.path, keep_frac=0.3)
        # an explicitly pinned version must NOT silently fall back
        with pytest.raises(CheckpointCorruptError):
            store.restore(2)
        assert os.path.exists(i2.path + ".quarantine")
        # ...while the unpinned path still serves the survivor
        assert store.restore_with_info()[1].version == 1

    def test_store_with_no_intact_versions(self, tmp_path):
        from deeplearning4j_tpu.testing.chaos import truncate_file

        net, store, (i1,) = self._seed(tmp_path, n=1)
        truncate_file(i1.path, keep_frac=0.3)
        with pytest.raises(FileNotFoundError, match="no intact versions"):
            store.restore()

    def test_ids_monotonic_past_quarantine(self, tmp_path):
        from deeplearning4j_tpu.testing.chaos import truncate_file

        net, store, (i1, i2) = self._seed(tmp_path)
        truncate_file(i2.path, keep_frac=0.4)
        store.restore()  # quarantines v2, serves v1
        assert store.save(net).version == 3
        # a FRESH store over the directory still counts the quarantined id
        fresh = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        assert fresh.save(net).version == 4

    def test_stale_tmp_from_dead_writer_swept(self, tmp_path):
        dead_pid = 2**22 + 1  # linux pid_max caps at 2**22: can't be alive
        torn = tmp_path / f".tmp-v00000002-{dead_pid}"
        torn.write_bytes(b"torn write, never completed")
        live = tmp_path / f".tmp-v00000003-{os.getpid()}"
        live.write_bytes(b"in-flight async writer")
        store = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        names = set(os.listdir(tmp_path))
        assert torn.name not in names
        assert torn.name + ".quarantine" in names
        # a tmp owned by a LIVE pid is someone's in-flight write: untouched
        assert live.name in names
        assert store._m_corrupt.value == 1
        net = MultiLayerNetwork(_conf()).init()
        assert store.save(net).version == 1

    def test_load_into_falls_back_past_corrupt_latest(self, tmp_path):
        from deeplearning4j_tpu.testing.chaos import corrupt_file

        rng = np.random.default_rng(11)
        xs, ys = _windows(rng, 2)
        net = MultiLayerNetwork(_conf()).init()
        store = CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        net.fit_on_device(xs[0], ys[0])
        store.save(net)
        good_params = jax.tree_util.tree_map(np.asarray, net.params)
        net.fit_on_device(xs[1], ys[1])
        info2 = store.save(net)
        corrupt_file(info2.path, seed=3)
        loaded = store.load_into(net, fallback=True)
        assert loaded == 1
        _leaves_equal(net.params, good_params)
        assert os.path.exists(info2.path + ".quarantine")
