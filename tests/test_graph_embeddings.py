"""Graph package tests (reference: deeplearning4j-graph src/test — DeepWalk,
random walk, loader tests)."""

import numpy as np
import pytest

from deeplearning4j_tpu.graph_embeddings import (
    DeepWalk,
    EXCEPTION_ON_DISCONNECTED,
    Graph,
    GraphHuffman,
    GraphVectors,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
    generate_walks,
    load_adjacency_list,
    load_undirected_graph_edge_list,
    load_weighted_edge_list,
)


def _two_cliques(k=5):
    """Two k-cliques joined by one bridge edge — classic DeepWalk test shape."""
    g = Graph(2 * k)
    for a in range(k):
        for b in range(a + 1, k):
            g.add_edge(a, b)
            g.add_edge(k + a, k + b)
    g.add_edge(0, k)  # bridge
    return g


class TestGraph:
    def test_edges_and_degree(self):
        g = Graph(4)
        g.add_edge(0, 1)
        g.add_edge(1, 2, directed=True)
        assert g.get_connected_vertex_indices(0) == [1]
        assert set(g.get_connected_vertex_indices(1)) == {0, 2}
        assert g.get_connected_vertex_indices(2) == []  # directed: no back edge
        assert g.get_vertex_degree(1) == 2
        with pytest.raises(ValueError):
            g.add_edge(0, 9)

    def test_loaders(self, tmp_path):
        p = tmp_path / "edges.txt"
        p.write_text("# comment\n0 1\n1 2\n")
        g = load_undirected_graph_edge_list(str(p), 3)
        assert set(g.get_connected_vertex_indices(1)) == {0, 2}

        pw = tmp_path / "weighted.txt"
        pw.write_text("0 1 2.5\n1 2 0.5\n")
        gw = load_weighted_edge_list(str(pw), 3)
        assert gw.get_edges_out(0)[0].weight == 2.5

        pa = tmp_path / "adj.txt"
        pa.write_text("0 1 2\n1 0\n2 0\n")
        ga = load_adjacency_list(str(pa))
        assert ga.num_vertices() == 3
        assert set(ga.get_connected_vertex_indices(0)) == {1, 2}


class TestWalks:
    def test_walk_properties(self):
        g = _two_cliques()
        it = RandomWalkIterator(g, walk_length=8, seed=1)
        walks = list(it)
        assert len(walks) == g.num_vertices()  # one walk per start vertex
        assert sorted(w[0] for w in walks) == list(range(10))
        for w in walks:
            assert len(w) == 8
            for a, b in zip(w[:-1], w[1:]):
                assert b in g.get_connected_vertex_indices(a)

    def test_disconnected_handling(self):
        g = Graph(3)
        g.add_edge(0, 1)
        # vertex 2 isolated: self-loop mode keeps walking in place
        walks = list(RandomWalkIterator(g, walk_length=4, seed=0))
        w2 = next(w for w in walks if w[0] == 2)
        assert w2 == [2, 2, 2, 2]
        with pytest.raises(RuntimeError):
            list(RandomWalkIterator(g, 4, no_edge_handling=EXCEPTION_ON_DISCONNECTED))

    def test_weighted_walk_bias(self):
        g = Graph(3)
        g.add_edge(0, 1, weight=100.0)
        g.add_edge(0, 2, weight=0.01)
        counts = {1: 0, 2: 0}
        it = WeightedRandomWalkIterator(g, walk_length=2, seed=3)
        for _ in range(50):
            it.reset()
            for w in it:
                if w[0] == 0:
                    counts[w[1]] += 1
        assert counts[1] > 40  # overwhelmingly to the heavy edge

    def test_generate_walks_multi_pass(self):
        g = _two_cliques()
        walks = generate_walks(g, walk_length=5, walks_per_vertex=3, seed=0)
        assert len(walks) == 3 * g.num_vertices()


class TestGraphHuffman:
    def test_degree_tree(self):
        g = _two_cliques()
        h = GraphHuffman.from_graph(g)
        assert len(h.words) == g.num_vertices()
        # bridge endpoints (highest degree) get the shortest codes
        code_lens = {int(w.word): len(w.codes) for w in h.words}
        assert code_lens[0] <= max(code_lens.values())


class TestDeepWalk:
    def test_clique_structure_recovered(self):
        g = _two_cliques()
        dw = DeepWalk(vector_size=16, window=3, walk_length=20,
                      walks_per_vertex=8, epochs=3, learning_rate=0.05,
                      batch_size=256, seed=1)
        gv = dw.fit(g)
        assert gv.num_vertices() == 10
        # same-clique similarity should dominate cross-clique
        same = np.mean([gv.similarity(1, j) for j in range(2, 5)])
        cross = np.mean([gv.similarity(1, j) for j in range(6, 10)])
        assert same > cross, (same, cross)
        nearest = gv.vertices_nearest(2, top_n=4)
        assert sum(v < 5 for v in nearest) >= 3, nearest

    def test_graphvectors_save_load(self, tmp_path):
        g = _two_cliques()
        gv = GraphVectors(g, np.random.default_rng(0).normal(size=(10, 8)))
        path = str(tmp_path / "gv")
        gv.save(path)
        loaded = GraphVectors.load(path, g)
        np.testing.assert_allclose(loaded.vectors, gv.vectors)
