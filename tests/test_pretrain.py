"""Pretrain-layer tests: AutoEncoder, RBM, VAE (reference suites:
VaeGradientCheckTests, RBM/AutoEncoder tests under deeplearning4j-core)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (
    AutoEncoder,
    BernoulliReconstruction,
    CompositeReconstruction,
    DenseLayer,
    ExponentialReconstruction,
    GaussianReconstruction,
    InputType,
    LossFunctionWrapper,
    MultiLayerConfiguration,
    MultiLayerNetwork,
    OutputLayer,
    RBM,
    UpdaterConfig,
    VariationalAutoencoder,
)
from deeplearning4j_tpu.datasets.iterators import DataSet
from deeplearning4j_tpu.utils.gradcheck import gradient_check


def _binary_data(n=64, d=12, seed=0):
    rng = np.random.default_rng(seed)
    # two prototype patterns + noise -> learnable structure
    protos = rng.integers(0, 2, size=(2, d)).astype(np.float64)
    idx = rng.integers(0, 2, size=n)
    x = protos[idx]
    flip = rng.uniform(size=x.shape) < 0.05
    return np.abs(x - flip), idx


class TestAutoEncoder:
    def test_pretrain_reduces_reconstruction_loss(self):
        x, _ = _binary_data()
        ae = AutoEncoder(n_in=12, n_out=6, activation="sigmoid",
                         corruption_level=0.1, loss="mse")
        conf = MultiLayerConfiguration(
            layers=[ae, OutputLayer(n_in=6, n_out=2, activation="softmax")],
            input_type=InputType.feed_forward(12),
            updater=UpdaterConfig(updater="adam", learning_rate=0.01),
            seed=1,
        )
        net = MultiLayerNetwork(conf).init()
        p0 = net.params[0]
        loss0 = float(ae.pretrain_loss(p0, jnp.asarray(x)))
        net.pretrain(DataSet(x, None), epochs=60)
        loss1 = float(ae.pretrain_loss(net.params[0], jnp.asarray(x)))
        assert loss1 < loss0 * 0.6, (loss0, loss1)

    def test_pretrain_loss_gradcheck(self):
        ae = AutoEncoder(n_in=5, n_out=3, activation="sigmoid",
                         corruption_level=0.0, loss="mse")
        p = ae.init_params(jax.random.PRNGKey(0), InputType.feed_forward(5))
        x = np.random.default_rng(0).uniform(size=(4, 5))
        passed, nfail, err = gradient_check(
            lambda p, x: ae.pretrain_loss(p, x), p, jnp.asarray(x)
        )
        assert passed, (nfail, err)

    def test_sparsity_penalty(self):
        ae = AutoEncoder(n_in=5, n_out=3, activation="sigmoid", sparsity=0.05,
                         corruption_level=0.0)
        p = ae.init_params(jax.random.PRNGKey(0), InputType.feed_forward(5))
        x = jnp.asarray(np.random.default_rng(0).uniform(size=(4, 5)))
        plain = AutoEncoder(n_in=5, n_out=3, activation="sigmoid",
                            corruption_level=0.0)
        assert float(ae.pretrain_loss(p, x)) > float(plain.pretrain_loss(p, x))


class TestRBM:
    def test_cd_training_lowers_free_energy_gap(self):
        x, _ = _binary_data(n=128)
        rbm = RBM(n_in=12, n_out=8, k=1)
        conf = MultiLayerConfiguration(
            layers=[rbm, OutputLayer(n_in=8, n_out=2, activation="softmax")],
            input_type=InputType.feed_forward(12),
            updater=UpdaterConfig(updater="sgd", learning_rate=0.1),
            seed=1,
        )
        net = MultiLayerNetwork(conf).init()
        err0 = float(rbm.reconstruction_error(net.params[0], jnp.asarray(x)))
        net.pretrain(DataSet(x, None), epochs=100)
        err1 = float(rbm.reconstruction_error(net.params[0], jnp.asarray(x)))
        assert err1 < err0 * 0.7, (err0, err1)

    def test_prop_up_down_shapes(self):
        rbm = RBM(n_in=6, n_out=4)
        p = rbm.init_params(jax.random.PRNGKey(0), InputType.feed_forward(6))
        v = jnp.asarray(np.random.default_rng(0).uniform(size=(3, 6)))
        h = rbm.prop_up(p, v)
        assert h.shape == (3, 4)
        assert float(h.min()) >= 0 and float(h.max()) <= 1
        assert rbm.prop_down(p, h).shape == (3, 6)

    def test_gaussian_visible(self):
        rbm = RBM(n_in=6, n_out=4, visible_unit="gaussian")
        p = rbm.init_params(jax.random.PRNGKey(0), InputType.feed_forward(6))
        x = jnp.asarray(np.random.default_rng(0).normal(size=(3, 6)))
        loss = rbm.pretrain_loss(p, x, jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))


class TestVAE:
    def _vae(self, recon=None, n_in=8, n_z=3):
        return VariationalAutoencoder(
            n_in=n_in, n_out=n_z,
            encoder_layer_sizes=(16,), decoder_layer_sizes=(16,),
            activation="tanh", num_samples=1,
            reconstruction=recon or BernoulliReconstruction(),
        )

    def test_elbo_gradcheck(self):
        vae = self._vae()
        p = vae.init_params(jax.random.PRNGKey(0), InputType.feed_forward(8))
        x = jnp.asarray((np.random.default_rng(0).uniform(size=(4, 8)) > 0.5).astype(float))
        rng = jax.random.PRNGKey(7)  # fixed sampling noise -> deterministic loss
        passed, nfail, err = gradient_check(
            lambda p, x: vae.pretrain_loss(p, x, rng), p, x
        )
        assert passed, (nfail, err)

    @pytest.mark.parametrize(
        "recon,data",
        [
            (BernoulliReconstruction(), "binary"),
            (GaussianReconstruction(), "real"),
            (ExponentialReconstruction(), "positive"),
            (LossFunctionWrapper(loss="mse"), "real"),
            (
                CompositeReconstruction(
                    parts=[(4, BernoulliReconstruction()), (4, GaussianReconstruction())]
                ),
                "binary",
            ),
        ],
    )
    def test_all_reconstruction_distributions(self, recon, data):
        rng = np.random.default_rng(0)
        if data == "binary":
            x = (rng.uniform(size=(6, 8)) > 0.5).astype(np.float64)
        elif data == "positive":
            x = rng.exponential(size=(6, 8))
        else:
            x = rng.normal(size=(6, 8))
        vae = self._vae(recon=recon)
        p = vae.init_params(jax.random.PRNGKey(0), InputType.feed_forward(8))
        loss = vae.pretrain_loss(p, jnp.asarray(x), jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))
        # mean path produces data-shaped output
        z = jnp.zeros((6, 3))
        assert vae.generate_at_mean_given_z(p, z).shape == (6, 8)

    def test_vae_pretrain_improves_elbo(self):
        x, _ = _binary_data(n=128, d=8)
        vae = self._vae()
        conf = MultiLayerConfiguration(
            layers=[vae, OutputLayer(n_in=3, n_out=2, activation="softmax")],
            input_type=InputType.feed_forward(8),
            updater=UpdaterConfig(updater="adam", learning_rate=0.01),
            seed=1,
        )
        net = MultiLayerNetwork(conf).init()
        key = jax.random.PRNGKey(5)
        loss0 = float(vae.pretrain_loss(net.params[0], jnp.asarray(x), key))
        net.pretrain(DataSet(x, None), epochs=80)
        loss1 = float(vae.pretrain_loss(net.params[0], jnp.asarray(x), key))
        assert loss1 < loss0, (loss0, loss1)

    def test_reconstruction_log_probability(self):
        x, _ = _binary_data(n=16, d=8)
        vae = self._vae()
        p = vae.init_params(jax.random.PRNGKey(0), InputType.feed_forward(8))
        logp = vae.reconstruction_log_probability(p, jnp.asarray(x), num_samples=16)
        assert logp.shape == (16,)
        assert np.all(np.asarray(logp) < 0)

    def test_vae_json_roundtrip(self):
        vae = self._vae(
            recon=CompositeReconstruction(
                parts=[(4, BernoulliReconstruction()), (4, GaussianReconstruction())]
            )
        )
        conf = MultiLayerConfiguration(
            layers=[vae, OutputLayer(n_in=3, n_out=2, activation="softmax")],
            input_type=InputType.feed_forward(8),
        )
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        vae2 = conf2.layers[0]
        assert isinstance(vae2, VariationalAutoencoder)
        assert isinstance(vae2.reconstruction, CompositeReconstruction)
        assert vae2.encoder_layer_sizes == (16,)
        p = vae.init_params(jax.random.PRNGKey(0), InputType.feed_forward(8))
        x = jnp.asarray((np.random.default_rng(0).uniform(size=(4, 8)) > 0.5).astype(float))
        k = jax.random.PRNGKey(1)
        np.testing.assert_allclose(
            float(vae.pretrain_loss(p, x, k)), float(vae2.pretrain_loss(p, x, k))
        )


class TestSupervisedAfterPretrain:
    def test_pretrain_then_finetune(self):
        x, idx = _binary_data(n=128, d=12)
        y = np.eye(2)[idx]
        conf = MultiLayerConfiguration(
            layers=[
                AutoEncoder(n_in=12, n_out=6, activation="sigmoid", corruption_level=0.1),
                OutputLayer(n_in=6, n_out=2, activation="softmax", loss="mcxent"),
            ],
            input_type=InputType.feed_forward(12),
            updater=UpdaterConfig(updater="adam", learning_rate=0.01),
            seed=1,
        )
        net = MultiLayerNetwork(conf).init()
        net.pretrain(DataSet(x, None), epochs=30)
        net.fit(DataSet(x, y), epochs=30)
        assert net.evaluate([DataSet(x, y)]).accuracy() > 0.95
