"""Conv-family tests: gradient checks + shape inference + LeNet training.

Mirrors the reference's CNNGradientCheckTest / BNGradientCheckTest /
LRNGradientCheckTests / GlobalPoolingGradientCheckTests (SURVEY.md §4.1) and
the deterministic LeNet-MNIST integration pattern (§4.2).
"""

import numpy as np
import pytest

from deeplearning4j_tpu import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    GlobalPoolingLayer,
    InputType,
    LocalResponseNormalization,
    MultiLayerConfiguration,
    MultiLayerNetwork,
    NumpyDataSetIterator,
    OutputLayer,
    SubsamplingLayer,
    UpdaterConfig,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import conv_output_size
from deeplearning4j_tpu.utils.gradcheck import gradient_check


def image_data(n=6, h=8, w=8, c=2, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, h, w, c))
    y = np.eye(classes)[rng.integers(0, classes, size=n)]
    return x, y


def build(layers, h=8, w=8, c=2):
    conf = MultiLayerConfiguration(
        layers=layers,
        input_type=InputType.convolutional(h, w, c),
        updater=UpdaterConfig(updater="sgd", learning_rate=0.1),
        seed=7,
    )
    return MultiLayerNetwork(conf).init()


class TestShapeInference:
    def test_conv_output_size_rules(self):
        # truncate: floor((in - k + 2p)/s) + 1
        assert conv_output_size(28, 5, 1, 0, "truncate") == 24
        assert conv_output_size(7, 3, 2, 0, "truncate") == 3
        # same: ceil(in/s)
        assert conv_output_size(28, 5, 1, 0, "same") == 28
        assert conv_output_size(7, 3, 2, 0, "same") == 4
        # strict raises on non-divisible
        with pytest.raises(ValueError):
            conv_output_size(8, 3, 2, 0, "strict")  # (8-3) % 2 != 0
        assert conv_output_size(7, 3, 2, 0, "strict") == 3  # divisible: ok

    def test_network_shape_chain(self):
        net = build(
            [
                ConvolutionLayer(n_out=4, kernel=(3, 3), convolution_mode="same"),
                SubsamplingLayer(kernel=(2, 2), stride=(2, 2)),
                ConvolutionLayer(n_out=8, kernel=(3, 3)),
                GlobalPoolingLayer(pooling_type="avg"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
            ]
        )
        its = net.conf.layer_input_types()
        assert its[1].example_shape() == (8, 8, 4)  # same conv keeps 8x8
        assert its[2].example_shape() == (4, 4, 4)  # pooled
        assert its[3].example_shape() == (2, 2, 8)  # valid 3x3
        assert its[4].flat_size() == 8  # global pooled to channels
        out = net.output(np.zeros((2, 8, 8, 2), np.float32))
        assert out.shape == (2, 3)

    def test_zero_padding(self):
        net = build(
            [
                ZeroPaddingLayer(pad_top=1, pad_bottom=2, pad_left=3, pad_right=0),
                GlobalPoolingLayer(pooling_type="sum"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
            ]
        )
        its = net.conf.layer_input_types()
        assert its[1].example_shape() == (11, 11, 2)


class TestGradients:
    def check(self, net, x, y, budget=60):
        ok, failures, max_rel = gradient_check(
            net.loss_fn, net.params, x, y, max_params_to_check=budget, verbose=True
        )
        assert ok, f"{failures} failures, max rel {max_rel:.3g}"

    def test_conv_truncate(self):
        x, y = image_data()
        net = build(
            [
                ConvolutionLayer(n_out=3, kernel=(3, 3), activation="tanh"),
                GlobalPoolingLayer(pooling_type="avg"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
            ]
        )
        self.check(net, x, y)

    def test_conv_same_strided(self):
        x, y = image_data()
        net = build(
            [
                ConvolutionLayer(
                    n_out=3, kernel=(3, 3), stride=(2, 2), convolution_mode="same",
                    activation="sigmoid",
                ),
                GlobalPoolingLayer(pooling_type="sum"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
            ]
        )
        self.check(net, x, y)

    @pytest.mark.parametrize("pool", ["max", "avg", "sum"])
    def test_subsampling(self, pool):
        x, y = image_data(seed=2)
        net = build(
            [
                ConvolutionLayer(n_out=3, kernel=(3, 3), activation="tanh"),
                SubsamplingLayer(pooling_type=pool, kernel=(2, 2), stride=(2, 2)),
                GlobalPoolingLayer(pooling_type="avg"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
            ]
        )
        self.check(net, x, y)

    def test_batchnorm_train_mode(self):
        x, y = image_data(seed=3)
        net = build(
            [
                ConvolutionLayer(n_out=3, kernel=(3, 3), activation="identity"),
                BatchNormalization(activation="relu"),
                GlobalPoolingLayer(pooling_type="avg"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
            ]
        )
        loss_train = lambda p, xx, yy: net.loss_fn(p, xx, yy, train=True)
        ok, failures, max_rel = gradient_check(
            loss_train, net.params, x + 0.05 * np.sign(x), y,
            max_params_to_check=60, verbose=True,
        )
        assert ok, f"{failures} BN failures, max rel {max_rel:.3g}"

    def test_batchnorm_bf16_stays_bf16(self):
        """f32 running stats must not promote the activation tensor: the
        per-channel scale/offset fold keeps eval AND train elementwise work
        in the compute dtype (a bf16 eval pass used to silently upcast the
        whole NHWC tensor to f32 — pure HBM waste on TPU)."""
        import jax.numpy as jnp

        bn = BatchNormalization()
        from deeplearning4j_tpu.nn.conf.inputs import InputType as IT

        it = IT.convolutional(8, 8, 4)
        import jax

        params = bn.init_params(jax.random.PRNGKey(0), it)
        state = bn.init_state(it)  # f32/f64 running stats
        x = jnp.ones((2, 8, 8, 4), jnp.bfloat16)
        for train in (False, True):
            y, new_state = bn.apply(params, x, state, train=train)
            assert y.dtype == jnp.bfloat16, (train, y.dtype)
            # running stats keep their high precision
            assert new_state["mean"].dtype == state["mean"].dtype

    def test_batchnorm_f32_large_mean_variance_accurate(self):
        """Full-precision inputs keep the two-pass variance: the one-pass
        E[x^2]-E[x]^2 form cancels catastrophically at |mean| >> std (f32
        mean 1e4, std 1e-2 would lose var entirely), so it is reserved for
        bf16/f16 inputs whose f32 accumulators out-precision the data."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.conf.inputs import InputType as IT

        bn = BatchNormalization()
        it = IT.feed_forward(4)
        params = bn.init_params(jax.random.PRNGKey(0), it)
        state = bn.init_state(it)
        rng = np.random.default_rng(0)
        x = (1e4 + 1e-2 * rng.normal(size=(4096, 4))).astype(np.float32)
        _, new_state = bn.apply(params, jnp.asarray(x), state, train=True)
        batch_var = (1 - bn.decay) ** -1 * (
            np.asarray(new_state["var"]) - bn.decay * np.asarray(state["var"])
        )
        np.testing.assert_allclose(batch_var, x.var(axis=0), rtol=1e-2)

    def test_lrn(self):
        x, y = image_data(c=6, seed=4)
        net = build(
            [
                LocalResponseNormalization(),
                GlobalPoolingLayer(pooling_type="avg"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
            ],
            c=6,
        )
        self.check(net, x, y)

    @pytest.mark.parametrize("pool", ["max", "avg", "sum", "pnorm"])
    def test_global_pooling_types(self, pool):
        x, y = image_data(seed=5)
        net = build(
            [
                ConvolutionLayer(n_out=3, kernel=(3, 3), activation="tanh"),
                GlobalPoolingLayer(pooling_type=pool),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
            ]
        )
        self.check(net, x, y, budget=40)


class TestPoolingSemantics:
    def test_avg_pool_excludes_padding(self):
        """Same-mode avg pooling divides by real-element count, not kernel area."""
        x = np.ones((1, 4, 4, 1), np.float64)
        net = build(
            [
                SubsamplingLayer(
                    pooling_type="avg", kernel=(3, 3), stride=(1, 1),
                    convolution_mode="same",
                ),
                GlobalPoolingLayer(pooling_type="sum"),
                OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
            ],
            h=4, w=4, c=1,
        )
        acts = net.feed_forward(x)
        pooled = np.asarray(acts[0])
        # all-ones input: every window averages to exactly 1.0 incl. borders
        np.testing.assert_allclose(pooled, 1.0, rtol=1e-12)

    def test_same_mode_rejects_explicit_padding(self):
        with pytest.raises(ValueError, match="same"):
            build(
                [
                    ConvolutionLayer(
                        n_out=2, kernel=(3, 3), padding=(2, 2), convolution_mode="same"
                    ),
                    GlobalPoolingLayer(pooling_type="avg"),
                    OutputLayer(n_out=2, loss="mcxent"),
                ]
            ).conf.layer_input_types()

    def test_global_pooling_respects_time_mask(self):
        """Masked timesteps excluded (reference: MaskedReductionUtil)."""
        from deeplearning4j_tpu import DataSet
        from deeplearning4j_tpu.nn.conf.inputs import InputType as IT

        conf = MultiLayerConfiguration(
            layers=[
                GlobalPoolingLayer(pooling_type="avg"),
                OutputLayer(n_out=2, activation="softmax", loss="mcxent"),
            ],
            input_type=IT.recurrent(3, 4),
        )
        net = MultiLayerNetwork(conf).init()
        x = np.zeros((2, 4, 3))
        x[:, :2] = 1.0  # real steps are all-ones
        x[:, 2:] = 99.0  # padded steps are garbage
        mask = np.zeros((2, 4))
        mask[:, :2] = 1.0
        acts_masked = net._forward(net.params, x, net.state, False, None,
                                   upto=1, features_mask=mask)[0]
        np.testing.assert_allclose(np.asarray(acts_masked), 1.0, rtol=1e-12)


class TestBatchNormState:
    def test_running_stats_update_and_freeze(self):
        x, y = image_data(n=16, seed=6)
        net = build(
            [
                BatchNormalization(decay=0.5),
                GlobalPoolingLayer(pooling_type="avg"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
            ]
        )
        m0 = np.asarray(net.state[0]["mean"]).copy()
        net.fit((x, y))
        m1 = np.asarray(net.state[0]["mean"])
        assert not np.allclose(m0, m1), "running mean did not update during training"
        # inference must not mutate state
        net.output(x[:4])
        m2 = np.asarray(net.state[0]["mean"])
        np.testing.assert_array_equal(m1, m2)

    def test_bn_json_round_trip(self):
        conf = MultiLayerConfiguration(
            layers=[
                BatchNormalization(decay=0.8, eps=1e-3),
                OutputLayer(n_out=2, loss="mse"),
            ],
            input_type=InputType.feed_forward(5),
        )
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        assert conf2.layers[0].decay == 0.8
        assert conf2.layers[0].eps == 1e-3


class TestLeNet:
    def test_lenet_trains_on_synthetic_mnist(self):
        from deeplearning4j_tpu.models.lenet import lenet_mnist_conf

        rng = np.random.default_rng(0)
        n, classes = 64, 10
        y_idx = rng.integers(0, classes, size=n)
        # class-dependent blobs so the problem is learnable
        x = rng.normal(size=(n, 28, 28, 1)) * 0.1
        for i, c in enumerate(y_idx):
            x[i, (c * 2) % 28 : (c * 2) % 28 + 4, (c * 3) % 24 : (c * 3) % 24 + 4, 0] += 2.0
        y = np.eye(classes)[y_idx]

        conf = lenet_mnist_conf(learning_rate=2e-3, seed=3)
        net = MultiLayerNetwork(conf)
        from deeplearning4j_tpu import CollectScoresIterationListener

        scores = CollectScoresIterationListener()
        net.set_listeners(scores)
        net.fit(NumpyDataSetIterator(x, y, batch=32, shuffle=True), epochs=12)
        assert scores.scores[-1][1] < scores.scores[0][1] * 0.5
        ev = net.evaluate(NumpyDataSetIterator(x, y, batch=32))
        assert ev.accuracy() > 0.8, ev.stats()
