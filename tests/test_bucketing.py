"""Variable-length sequences with bounded recompiles (SURVEY §7 hard part f).

XLA compiles one program per input shape; a ragged NLP corpus naively padded
to each batch's max length causes a recompile storm. These tests pin the
mitigation: BucketingSequenceIterator bounds fit() compiles to its
num_programs() upper bound, and pad_to_bucket + the rnn_time_step mask bound
streaming-inference compiles to len(boundaries) while keeping the recurrent
state exactly what the real (unpadded) steps produce.
"""

import jax
import numpy as np

from deeplearning4j_tpu import (
    GravesLSTM,
    InputType,
    MultiLayerConfiguration,
    MultiLayerNetwork,
    RnnOutputLayer,
    UpdaterConfig,
)
from deeplearning4j_tpu.datasets.iterators import (
    BucketingSequenceIterator,
    pad_to_bucket,
)

BOUNDS = (8, 16, 32)


def _rnn_net(seed=0):
    conf = MultiLayerConfiguration(
        layers=[
            GravesLSTM(n_out=8, activation="tanh"),
            RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.recurrent(4),
        updater=UpdaterConfig(updater="adam", learning_rate=1e-2),
        seed=seed,
    )
    return MultiLayerNetwork(conf).init()


def _ragged_corpus(n=40, seed=0):
    rng = np.random.default_rng(seed)
    seqs = []
    for _ in range(n):
        t = int(rng.integers(3, 30))
        feats = rng.normal(size=(t, 4)).astype(np.float32)
        labels = np.eye(3, dtype=np.float32)[rng.integers(0, 3, t)]
        seqs.append((feats, labels))
    return seqs


def test_bucketing_bounds_fit_compiles():
    """Two epochs over a 27-distinct-length corpus compile at most
    num_programs() traces (<= buckets + trailing partials), not one per
    distinct batch-max length."""
    seqs = _ragged_corpus()
    it = BucketingSequenceIterator(seqs, batch=8, boundaries=BOUNDS)
    net = _rnn_net()
    net.fit(it, epochs=2)
    bound = it.num_programs()
    assert bound <= 2 * len(BOUNDS)
    compiles = net._train_step._cache_size()
    assert compiles <= bound, (compiles, bound)
    distinct_lengths = len({f.shape[0] for f, _ in seqs})
    assert distinct_lengths > bound  # the storm the iterator prevents


def test_bucketing_iterator_masks_and_order():
    it = BucketingSequenceIterator(_ragged_corpus(), batch=8, boundaries=BOUNDS)
    seen = 0
    for ds in it:
        b, t, f = ds.features.shape
        assert t in BOUNDS and f == 4
        assert ds.features_mask.shape == (b, t)
        assert ds.labels_mask.shape == (b, t)
        # mask is a prefix run of ones; features zero beyond it
        for i in range(b):
            n_real = int(ds.features_mask[i].sum())
            assert ds.features_mask[i, :n_real].all()
            assert not ds.features_mask[i, n_real:].any()
            assert not ds.features[i, n_real:].any()
        seen += b
    assert seen == 40


def test_pad_to_bucket_streaming_bounds_compiles_and_preserves_state():
    net = _rnn_net(seed=7)
    rng = np.random.default_rng(1)
    for t in (5, 9, 17, 3, 30, 12, 7):
        x = rng.normal(size=(2, t, 4)).astype(np.float32)
        xp, mask, real_t = pad_to_bucket(x, BOUNDS)
        assert real_t == t and xp.shape[1] in BOUNDS
        out = np.asarray(net.rnn_time_step(xp, features_mask=mask))[:, :t]
        assert out.shape == (2, t, 3)
    # one program per touched bucket, regardless of the 7 distinct lengths
    # (PR 7: streaming programs are AOT entries in the process compile
    # manager, keyed by the net's owner token, not a per-net jit cache)
    from deeplearning4j_tpu.runtime.compile_manager import get_compile_manager

    cm = get_compile_manager()
    programs = [k for k in cm._entries
                if isinstance(k, tuple) and k and k[0] == net._cm_token
                and cm._key_kind(k) == "mln_rnn_step"]
    assert len(programs) <= len(BOUNDS)

    # masked padded steps hold h/c: state equals the exact-length run's
    exact = _rnn_net(seed=7)
    x = rng.normal(size=(2, 11, 4)).astype(np.float32)
    exact_out = np.asarray(exact.rnn_time_step(x))
    exact_state = exact._rnn_state

    net.rnn_clear_previous_state()
    xp, mask, t = pad_to_bucket(x, BOUNDS)
    padded_out = np.asarray(net.rnn_time_step(xp, features_mask=mask))[:, :t]
    np.testing.assert_allclose(padded_out, exact_out, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(exact_state),
                    jax.tree_util.tree_leaves(net._rnn_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_pad_to_bucket_overlong_raises():
    x = np.zeros((1, 40, 4), np.float32)
    try:
        pad_to_bucket(x, BOUNDS)
    except ValueError as e:
        assert "40" in str(e) and "32" in str(e)
    else:
        raise AssertionError("expected ValueError for overlong sequence")


def test_graph_rnn_time_step_masked_bucketing():
    from deeplearning4j_tpu.nn.conf.computation_graph import (
        ComputationGraphConfiguration,
    )
    from deeplearning4j_tpu.nn.graph.computation_graph import ComputationGraph

    conf = (
        ComputationGraphConfiguration.builder()
        .seed(5)
        .updater(UpdaterConfig(updater="adam", learning_rate=1e-2))
        .add_inputs("in")
        .add_layer("lstm", GravesLSTM(n_out=8, activation="tanh"), "in")
        .add_layer("out", RnnOutputLayer(n_out=3, activation="softmax",
                                         loss="mcxent"), "lstm")
        .set_outputs("out")
        .set_input_types(InputType.recurrent(4))
        .build()
    )
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(2)
    for t in (5, 13, 20, 4):
        x = rng.normal(size=(2, t, 4)).astype(np.float32)
        xp, mask, real_t = pad_to_bucket(x, BOUNDS)
        out = np.asarray(net.rnn_time_step(xp, features_masks=mask))[:, :real_t]
        assert out.shape == (2, t, 3)
    from deeplearning4j_tpu.runtime.compile_manager import get_compile_manager

    cm = get_compile_manager()
    programs = [k for k in cm._entries
                if isinstance(k, tuple) and k and k[0] == net._cm_token
                and cm._key_kind(k) == "graph_rnn_step"]
    assert len(programs) <= len(BOUNDS)
