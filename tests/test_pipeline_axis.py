"""Pipeline mesh axis (ISSUE 18): the 1F1B micro-batch interleaved schedule
through MeshLayout(pipe=N) + PipelinedTrainer.

The bar matches PR 15's seq axis: trajectory parity against the unpiped
trainer (the schedule reorders work, not math), predicted-vs-measured
collective census parity (the static flow pass must follow the pipelined
shard_map natively), cost-balanced stage partitioning beating equal-count
on a skewed model, the HBM preflight catching an over-stash micro-batch
count BEFORE any compile, and zero warm compiles on the fit path.
"""

import numpy as np
import pytest

from deeplearning4j_tpu import (
    DenseLayer,
    InputType,
    MultiLayerConfiguration,
    MultiLayerNetwork,
    OutputLayer,
    UpdaterConfig,
)
from deeplearning4j_tpu.datasets.iterators import DataSet
from deeplearning4j_tpu.parallel import MeshLayout, PipelinedTrainer, plan_stages
from deeplearning4j_tpu.runtime.compile_manager import get_compile_manager


def _dense_net(hidden=32, feat=16, classes=8, depth=3, seed=7):
    return MultiLayerNetwork(MultiLayerConfiguration(
        layers=[DenseLayer(n_out=hidden, activation="relu")
                for _ in range(depth)]
        + [OutputLayer(n_out=classes, activation="softmax", loss="mcxent")],
        input_type=InputType.feed_forward(feat),
        updater=UpdaterConfig(updater="adam", learning_rate=1e-2),
        seed=seed,
    )).init()


def _char_net(vocab=12, hidden=16, seed=3):
    """charrnn-shaped stacked LSTM, but with DEFAULT backprop: tbptt
    truncation would change the unpiped reference's math, and the parity
    oracle needs both sides computing the same loss."""
    from deeplearning4j_tpu.nn.layers.recurrent import GravesLSTM, RnnOutputLayer

    return MultiLayerNetwork(MultiLayerConfiguration(
        layers=[GravesLSTM(n_in=vocab, n_out=hidden, activation="tanh"),
                GravesLSTM(n_in=hidden, n_out=hidden, activation="tanh"),
                RnnOutputLayer(n_in=hidden, n_out=vocab,
                               activation="softmax", loss="mcxent")],
        input_type=InputType.recurrent(vocab),
        updater=UpdaterConfig(updater="adam", learning_rate=1e-2),
        seed=seed,
    )).init()


def _dense_batch(b=32, feat=16, classes=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(b, feat)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, b)]
    return x, y


def _assert_params_close(piped_net, ref_net, rtol=2e-4):
    import jax

    for i, (a, b) in enumerate(zip(jax.tree_util.tree_leaves(piped_net.params),
                                   jax.tree_util.tree_leaves(ref_net.params))):
        np.testing.assert_allclose(np.asarray(a, np.float64),
                                   np.asarray(b, np.float64),
                                   rtol=rtol, atol=1e-6,
                                   err_msg=f"param leaf {i} diverged")


class TestTrajectoryParity:
    """The pipelined step must walk the SAME optimizer trajectory as the
    unpiped net — micro-batching, the tick schedule, ppermute handoffs and
    the packed parameter layout are all implementation detail."""

    def test_dense_piped_vs_unpiped(self):
        x, y = _dense_batch()
        tr = PipelinedTrainer(_dense_net(), MeshLayout(data=2, pipe=2),
                              microbatches=4)
        losses = tr.fit(x, y, steps=3)
        assert np.all(np.isfinite(losses))
        tr.unpack_to_net()

        ref = _dense_net()
        ref.fit(DataSet(x, y), epochs=3)
        _assert_params_close(tr.net, ref)

    def test_charrnn_piped_vs_unpiped(self):
        vocab, b, t = 12, 16, 6
        rng = np.random.default_rng(1)
        x = np.eye(vocab, dtype=np.float32)[
            rng.integers(0, vocab, (b, t))]
        y = np.eye(vocab, dtype=np.float32)[
            rng.integers(0, vocab, (b, t))]
        tr = PipelinedTrainer(_char_net(vocab), MeshLayout(data=2, pipe=2),
                              microbatches=4)
        losses = tr.fit(x, y, steps=2)
        assert np.all(np.isfinite(losses))
        tr.unpack_to_net()

        ref = _char_net(vocab)
        ref.fit(DataSet(x, y), epochs=2)
        _assert_params_close(tr.net, ref)


class TestCensusParity:
    """The static flow pass walks the pipelined shard_map natively: its
    predicted census (per-microbatch ppermute attribution included) must
    match the collectives parsed from the compiled step's post-SPMD HLO."""

    @pytest.mark.parametrize("layout_kw", [
        {"data": 2, "pipe": 2},
        {"tp": 2, "pipe": 2},
    ], ids=["pipe_x_dp", "pipe_x_tp"])
    def test_predicted_matches_measured(self, layout_kw):
        from deeplearning4j_tpu.analysis.shard_flow import compare_census

        x, y = _dense_batch()
        tr = PipelinedTrainer(_dense_net(), MeshLayout(**layout_kw),
                              microbatches=4)
        flow = tr.analyze(x, y)
        assert flow["findings"] == [], \
            [f.format_human() for f in flow["findings"]]
        assert any(r["kind"] == "collective_permute"
                   and r["axes"] == ["pipe"] for r in flow["census"]), \
            flow["census"]
        res = compare_census(flow["census"], tr.measured_census(x, y))
        assert res["ok"], (res["problems"], flow["census"])


class TestStagePartitioning:
    def test_cost_balanced_beats_equal_count(self):
        """Skewed model: two wide layers up front, two narrow behind. The
        equal-count split pairs the wide ones on stage 0; the FLOPs/bytes
        walker must do better."""
        net = MultiLayerNetwork(MultiLayerConfiguration(
            layers=[DenseLayer(n_out=256, activation="relu"),
                    DenseLayer(n_out=256, activation="relu"),
                    DenseLayer(n_out=16, activation="relu"),
                    DenseLayer(n_out=16, activation="relu"),
                    OutputLayer(n_out=8, activation="softmax",
                                loss="mcxent")],
            input_type=InputType.feed_forward(64),
            updater=UpdaterConfig(updater="adam", learning_rate=1e-3),
        )).init()
        balanced = plan_stages(net, 2, 32, balance=True)
        naive = plan_stages(net, 2, 32, balance=False)
        assert balanced.balanced and not naive.balanced
        assert naive.stages == ((0, 1), (2, 3))
        assert balanced.max_cost < naive.max_cost, (
            balanced.describe(), naive.describe())

    def test_needs_enough_layers(self):
        with pytest.raises(ValueError, match="stage"):
            plan_stages(_dense_net(depth=1), 4, 32)


class TestPreflight:
    def test_over_stash_microbatches_raises(self):
        """Every in-flight micro-batch stashes its stage activations; an
        over-eager microbatches= must fail the projection BEFORE a doomed
        compile, naming the worst stage."""
        from deeplearning4j_tpu.telemetry.memory import MemoryPreflightError

        x, y = _dense_batch()
        tr = PipelinedTrainer(_dense_net(), MeshLayout(data=2, pipe=2),
                              microbatches=4)
        rep = tr.preflight(x, y)
        peak = rep["pipeline"]["projected_peak_bytes_per_device"]
        assert rep["pipeline"]["in_flight"] == 4 + 2 - 1
        assert peak > 0
        with pytest.raises(MemoryPreflightError, match="micro-batch"):
            tr.preflight(x, y, limit_bytes=peak // 2)

    def test_stash_grows_with_microbatches(self):
        lo = MeshLayout(data=2, pipe=2)
        stash = []
        for m in (2, 8):
            x, y = _dense_batch(b=16 * m)
            tr = PipelinedTrainer(_dense_net(), lo, microbatches=m)
            rep = tr.preflight(x, y)
            stash.append(max(r["stash_bytes"]
                             for r in rep["pipeline"]["stages"]))
        # fixed micro-batch SIZE: every extra in-flight micro-batch stashes
        # another full set of stage residuals (M+P-1 of them total)
        assert stash[1] > stash[0], stash


class TestCompileDiscipline:
    def test_zero_warm_compiles(self):
        x, y = _dense_batch()
        tr = PipelinedTrainer(_dense_net(), MeshLayout(data=2, pipe=2),
                              microbatches=4)
        tr.warm_up(x, y)
        cm = get_compile_manager()
        before = cm.compiles.value
        tr.fit(x, y, steps=4)
        assert cm.compiles.value - before == 0


class TestLayoutContract:
    def test_seq_axis_rejected(self):
        with pytest.raises(ValueError, match="seq"):
            PipelinedTrainer(_dense_net(), MeshLayout(seq=2, pipe=2),
                             microbatches=2)

    def test_apply_directs_to_trainer(self):
        net = _dense_net()
        with pytest.raises(ValueError, match="PipelinedTrainer"):
            MeshLayout(data=2, pipe=2).apply(net)

    def test_knob_registered(self):
        from deeplearning4j_tpu.tune.knobs import get_knob

        knob = get_knob("pipe_microbatches")
        assert knob.default == 4 and knob.cost_hint == "memory"
        # the default seeds PipelinedTrainer(microbatches=None)
        tr = PipelinedTrainer(_dense_net(), MeshLayout(data=2, pipe=2))
        assert tr.microbatches == knob.default
