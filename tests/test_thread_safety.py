"""Threaded regression tests for the races the DT4xx self-apply fixed
(ISSUE 16, satellite 1). Each test hammers the exact code path that used
to mutate shared state lock-free and asserts EXACT counts afterwards —
a lost update (the classic ``+= 1`` read-modify-write race) shows up as
a count below the number of increments, so these fail loudly on a
regression instead of flaking.

CPython's GIL does not make ``x += 1`` atomic: the interpreter can switch
threads between the LOAD and the STORE, and these tests drive enough
iterations through real thread pools that an unlocked counter loses
updates often enough to matter. They are, like all races, probabilistic —
the deterministic guarantee is the DT400 lint (test_concurrency_lint.py);
this file proves the fixes hold up under live contention.
"""

import threading

import numpy as np
import pytest

from deeplearning4j_tpu.fleet.router import FleetRouter
from deeplearning4j_tpu.runtime.online import _Count
from deeplearning4j_tpu.serving import InferenceService
from deeplearning4j_tpu.streaming.embedded_kafka import EmbeddedKafkaBroker
from deeplearning4j_tpu.telemetry import MetricsRegistry
from deeplearning4j_tpu.telemetry.flight_recorder import FlightRecorder
from deeplearning4j_tpu.telemetry.watchdog import Watchdog

N_THREADS = 8
N_ITERS = 400


def _hammer(*fns, threads_per_fn=N_THREADS, iters=N_ITERS):
    """Run each fn in ``threads_per_fn`` threads, ``iters`` calls each;
    re-raise the first worker exception."""
    errors = []

    def loop(fn):
        try:
            for _ in range(iters):
                fn()
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    workers = [threading.Thread(target=loop, args=(fn,))
               for fn in fns for _ in range(threads_per_fn)]
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    if errors:
        raise errors[0]


class TestOnlineCount:
    def test_concurrent_inc_is_exact(self):
        class _Family:
            def inc(self, n):
                pass

        count = _Count(_Family())
        _hammer(lambda: count.inc(1))
        assert count.n == N_THREADS * N_ITERS


class TestWatchdog:
    def test_concurrent_emit_and_add_sink(self):
        seen = []
        seen_lock = threading.Lock()
        wd = Watchdog(sinks=[], registry=MetricsRegistry())

        def emit():
            wd.emit("loss-drift", 1, 2.0, 1.0, "drifting")

        def grow():
            def sink(event):
                with seen_lock:
                    seen.append(event)
            wd.add_sink(sink)

        _hammer(emit, grow, iters=N_ITERS // 4)
        assert len(wd.events) == N_THREADS * (N_ITERS // 4)
        assert len(wd.sinks) == N_THREADS * (N_ITERS // 4)

    def test_observe_rolling_median_vs_emit(self):
        wd = Watchdog(sinks=[], registry=MetricsRegistry())

        def observe():
            wd.observe(1, 0.5, 1.0, step_time_s=0.01)

        def emit():
            wd.emit("input-shift", 2, 3.0, 1.0, "shift")

        _hammer(observe, emit, iters=N_ITERS // 4)
        # no stall fired (constant step time), so every emit landed and
        # the step-time ring stayed bounded
        assert len(wd.events) == N_THREADS * (N_ITERS // 4)
        assert len(wd._step_times) <= 256


class TestFlightRecorder:
    def test_concurrent_dump_and_snapshot(self, tmp_path, monkeypatch):
        rec = FlightRecorder(capacity=64, dump_dir=str(tmp_path),
                             registry=MetricsRegistry(),
                             min_dump_interval_s=0.0)
        monkeypatch.setattr(rec, "bundle", lambda reason="manual": {})
        dumps = 32

        def dump():
            rec.dump(reason="manual")

        def snap():
            rec.snapshot()

        def record():
            rec.record("step", loss=0.1)

        _hammer(dump, snap, record, threads_per_fn=4, iters=dumps)
        assert len(rec.dumps) == 4 * dumps
        # every dump wrote a DISTINCT file: the sequence number is taken
        # under the lock, so two racing dumps cannot clobber one path
        assert len(set(rec.dumps)) == 4 * dumps


class TestInferenceServiceStats:
    def test_record_request_vs_stats_exact_counts(self):
        # the metrics callbacks race stats() from logits/argmax/decode
        # threads; entry counters must come out exact
        pytest.importorskip("jax")
        from tests.test_serving import _mlp

        svc = InferenceService(registry=MetricsRegistry(), max_delay_ms=1)
        try:
            svc.register("m", _mlp())

            def record():
                svc._record_request("m", 0.001)

            def batch():
                svc._record_batch("m", rows=2, requests=2, seconds=0.001,
                                  queue_depth=0)

            def stats():
                svc.stats()

            _hammer(record, batch, stats, threads_per_fn=4,
                    iters=N_ITERS // 4)
            snap = svc.stats()["models"]["m"]
            assert snap["requests_total"] == 4 * (N_ITERS // 4)
            assert snap["batches_total"] == 4 * (N_ITERS // 4)
            assert snap["rows_total"] == 2 * 4 * (N_ITERS // 4)
        finally:
            svc.stop()


class TestFleetRouterCounters:
    def test_failed_total_exact_without_workers(self, tmp_path):
        # route_predict with zero ready workers takes the failure path:
        # one failed_total bump per call, from many handler threads
        router = FleetRouter(str(tmp_path), workers=0,
                             registry=MetricsRegistry())

        def route():
            status, _body, _hdrs = router.route_predict({"features": []})
            assert status == 503

        def stats():
            router.stats()

        _hammer(route, stats, threads_per_fn=4, iters=N_ITERS // 4)
        assert router.failed_total == 4 * (N_ITERS // 4)


class TestEmbeddedKafkaTopics:
    def test_concurrent_topic_creation_and_append(self):
        broker = EmbeddedKafkaBroker(num_partitions=2)
        appended = 64

        def create():
            broker.create_topic("t")

        def append():
            broker.append("t", b"v", key=b"k")

        def partitions():
            assert len(broker.partitions_for("t")) == 2

        _hammer(create, append, partitions, threads_per_fn=4,
                iters=appended)
        total = sum(broker.end_offset(tp)
                    for tp in broker.partitions_for("t"))
        assert total == 4 * appended
