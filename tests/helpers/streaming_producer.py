"""Producer process for the socket streaming test: publish labelled records
to a SocketRecordSource across the process boundary (the NDArrayKafkaClient
role in the reference's Kafka pipeline)."""

import sys

import numpy as np

from deeplearning4j_tpu.streaming import serve_records


def main() -> int:
    host, port, n = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    rng = np.random.default_rng(0)
    labels = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    feats = (labels @ rng.normal(size=(3, 8))
             + 0.1 * rng.normal(size=(n, 8))).astype(np.float32)
    serve_records(host, port, list(zip(feats, labels)))
    print("PRODUCER_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
