"""Multi-process training worker for tests/test_multiprocess.py.

One OS process of an N-process jax.distributed CPU cluster — the analog of one
Spark executor in the reference's `local[n]` BaseSparkTest.java:90 pattern
scaled up to REAL process boundaries (SURVEY.md §4.3 prescribed exactly this:
``jax.distributed`` + virtual CPU devices as the multi-process test recipe).

Each process contributes ``--local-devices`` virtual CPU devices to one global
mesh; training data is generated identically on every process (the
driver-broadcast analog) and placed via ``global_put``; collectives ride Gloo.
Process 0 writes final params for the test to compare against a single-process
run of the same configuration.

Invoke only via the test (env must force the CPU platform before jax import).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--mode", choices=["sync", "periodic", "sync_localdata"],
                    default="periodic")
    ap.add_argument("--local-devices", type=int, default=2)
    args = ap.parse_args()

    import numpy as np

    from deeplearning4j_tpu.parallel.mesh import (
        initialize_multihost,
        make_mesh,
        replicated_sharding,
    )

    if args.num_processes > 1:
        initialize_multihost(
            coordinator_address=f"127.0.0.1:{args.port}",
            num_processes=args.num_processes,
            process_id=args.process_id,
        )

    import jax

    n_devices = args.local_devices * args.num_processes
    assert len(jax.devices()) == n_devices, (
        f"expected {n_devices} global devices, got {len(jax.devices())}"
    )

    from deeplearning4j_tpu import (
        DenseLayer,
        InputType,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        OutputLayer,
        UpdaterConfig,
    )
    from deeplearning4j_tpu.datasets.iterators import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.parallel.training_master import (
        ParameterAveragingTrainingMaster,
        SyncAllReduceTrainingMaster,
    )

    conf = MultiLayerConfiguration(
        layers=[
            DenseLayer(n_out=16, activation="tanh"),
            OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.feed_forward(6),
        updater=UpdaterConfig(updater="sgd", learning_rate=0.1),
        seed=11,
    )
    net = MultiLayerNetwork(conf).init()

    # Identical on every process — the broadcast analog. 3 averaging rounds of
    # n_devices minibatches each.
    rng = np.random.default_rng(99)
    batches = [
        DataSet(
            rng.normal(size=(8, 6)).astype(np.float32),
            np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=8)],
        )
        for _ in range(3 * n_devices)
    ]

    mesh = make_mesh(n_devices)
    master = None
    if args.mode == "periodic":
        master = ParameterAveragingTrainingMaster(averaging_frequency=2, mesh=mesh)
    elif args.mode == "sync_localdata":
        # per-host input pipeline (SURVEY §7(d)): THIS process feeds only its
        # contiguous share of each global step's batch, in per-device-sized
        # minibatches — the assembled global array is bit-identical to the
        # broadcast runs' (same examples, same order)
        from deeplearning4j_tpu.parallel import ParallelWrapper

        pidx, pcnt = jax.process_index(), jax.process_count()
        per_dev = batches[0].features.shape[0]  # one original batch per device
        local = []
        for k in range(0, len(batches), n_devices):
            step = batches[k : k + n_devices]
            gx = np.concatenate([b.features for b in step])
            gy = np.concatenate([b.labels for b in step])
            share = gx.shape[0] // pcnt
            lo = pidx * share
            for s in range(lo, lo + share, per_dev):
                local.append(DataSet(gx[s : s + per_dev], gy[s : s + per_dev]))
        wrapper = ParallelWrapper(net, mesh=mesh, data_is_local=True)
        wrapper.fit(ListDataSetIterator(local))
    else:
        master = SyncAllReduceTrainingMaster(mesh=mesh)
    if master is not None:
        master.execute_training(net, ListDataSetIterator(batches))
        stats = master.get_stats().summary()
        assert stats.get("fit", 0) > 0, f"no fit phase recorded: {stats}"

    # Gather replicated host values (resharding collective on multi-process).
    rep = replicated_sharding(mesh)
    flat = {}
    for i, layer in enumerate(jax.device_put(net.params, rep)):
        for k, v in (layer or {}).items():
            flat[f"{i}_{k}"] = np.asarray(jax.device_get(v), dtype=np.float64)
    loss = float(net._last_loss)

    if args.process_id == 0:
        np.savez(os.path.join(args.out, f"params_{args.mode}_{args.num_processes}p.npz"), **flat)
        with open(os.path.join(args.out, f"meta_{args.mode}_{args.num_processes}p.json"), "w") as f:
            json.dump({"loss": loss, "devices": n_devices,
                       "process_count": jax.process_count()}, f)
    print(f"WORKER_OK pid={args.process_id} loss={loss:.6f}", flush=True)


if __name__ == "__main__":
    main()
