"""Multi-process training worker for tests/test_multiprocess.py.

One OS process of an N-process jax.distributed CPU cluster — the analog of one
Spark executor in the reference's `local[n]` BaseSparkTest.java:90 pattern
scaled up to REAL process boundaries (SURVEY.md §4.3 prescribed exactly this:
``jax.distributed`` + virtual CPU devices as the multi-process test recipe).

Each process contributes ``--local-devices`` virtual CPU devices to one global
mesh; training data is generated identically on every process (the
driver-broadcast analog) and placed via ``global_put``; collectives ride Gloo.
Process 0 writes final params for the test to compare against a single-process
run of the same configuration.

Invoke only via the test (env must force the CPU platform before jax import —
build the child env with ``deeplearning4j_tpu.utils.subproc.forced_cpu_env``,
the one shared recipe; the assert in main() catches a caller that forgot).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def main() -> None:
    assert os.environ.get("JAX_PLATFORMS") == "cpu", (
        "spawn me with utils.subproc.forced_cpu_env() — the CPU platform "
        "must be pinned by env before the first jax import")
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--mode",
                    choices=["sync", "periodic", "sync_localdata", "dp_tp",
                             "recovery"],
                    default="periodic")
    ap.add_argument("--local-devices", type=int, default=2)
    # recovery-mode knobs (checkpoint-restart across a worker death):
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--start-round", type=int, default=0)
    ap.add_argument("--ckpt", default=None)
    ap.add_argument("--resume-from", default=None)
    ap.add_argument("--crash-rank", type=int, default=-1)
    ap.add_argument("--crash-after-round", type=int, default=-1)
    ap.add_argument("--tag", default="")
    args = ap.parse_args()

    import numpy as np

    from deeplearning4j_tpu.parallel.mesh import (
        initialize_multihost,
        make_mesh,
        replicated_sharding,
    )

    if args.num_processes > 1:
        initialize_multihost(
            coordinator_address=f"127.0.0.1:{args.port}",
            num_processes=args.num_processes,
            process_id=args.process_id,
        )

    import jax

    n_devices = args.local_devices * args.num_processes
    assert len(jax.devices()) == n_devices, (
        f"expected {n_devices} global devices, got {len(jax.devices())}"
    )

    from deeplearning4j_tpu import (
        DenseLayer,
        InputType,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        OutputLayer,
        UpdaterConfig,
    )
    from deeplearning4j_tpu.datasets.iterators import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.parallel.training_master import (
        ParameterAveragingTrainingMaster,
        SyncAllReduceTrainingMaster,
    )

    # recovery mode uses adam so a correct run REQUIRES updater-state-exact
    # resume (plain SGD would mask a dropped optimizer state)
    updater = ("adam" if args.mode == "recovery" else "sgd")
    conf = MultiLayerConfiguration(
        layers=[
            DenseLayer(n_out=16, activation="tanh"),
            OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.feed_forward(6),
        updater=UpdaterConfig(updater=updater, learning_rate=0.1),
        seed=11,
    )
    net = MultiLayerNetwork(conf).init()

    # Identical on every process — the broadcast analog. 3 averaging rounds of
    # n_devices minibatches each (recovery: --rounds rounds; dp_tp: global
    # batches sized for the data-parallel factor).
    rng = np.random.default_rng(99)

    def mk_batches(count, rows=8):
        return [
            DataSet(
                rng.normal(size=(rows, 6)).astype(np.float32),
                np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=rows)],
            )
            for _ in range(count)
        ]

    if args.mode == "recovery":
        batches = mk_batches(args.rounds * n_devices)
    elif args.mode == "dp_tp":
        batches = mk_batches(6, rows=8 * (n_devices // 2))
    else:
        batches = mk_batches(3 * n_devices)

    mesh = make_mesh(n_devices)
    master = None
    if args.mode == "periodic":
        master = ParameterAveragingTrainingMaster(averaging_frequency=2, mesh=mesh)
    elif args.mode == "sync_localdata":
        # per-host input pipeline (SURVEY §7(d)): THIS process feeds only its
        # contiguous share of each global step's batch, in per-device-sized
        # minibatches — the assembled global array is bit-identical to the
        # broadcast runs' (same examples, same order)
        from deeplearning4j_tpu.parallel import ParallelWrapper

        pidx, pcnt = jax.process_index(), jax.process_count()
        per_dev = batches[0].features.shape[0]  # one original batch per device
        local = []
        for k in range(0, len(batches), n_devices):
            step = batches[k : k + n_devices]
            gx = np.concatenate([b.features for b in step])
            gy = np.concatenate([b.labels for b in step])
            share = gx.shape[0] // pcnt
            lo = pidx * share
            for s in range(lo, lo + share, per_dev):
                local.append(DataSet(gx[s : s + per_dev], gy[s : s + per_dev]))
        wrapper = ParallelWrapper(net, mesh=mesh, data_is_local=True)
        wrapper.fit(ListDataSetIterator(local))
    elif args.mode == "dp_tp":
        # tensor parallelism ACROSS the process boundary: params GSPMD-shard
        # over the 'model' axis, batch over 'data' — with 2 processes x 2
        # devices the model axis spans both processes' devices, so the
        # tensor-parallel collectives ride the inter-process transport
        from deeplearning4j_tpu.parallel import ParallelWrapper

        dp = n_devices // 2
        tp_mesh = make_mesh(n_devices, axis_names=("data", "model"),
                            shape=(dp, 2))
        wrapper = ParallelWrapper(net, mesh=tp_mesh, model_axis="model")
        wrapper.fit(ListDataSetIterator(batches))
        mesh = tp_mesh
    elif args.mode == "recovery":
        # checkpoint-restart recovery: one sync averaging round per
        # execute_training call, checkpoint triple after every round; a
        # crashing rank dies AFTER round --crash-after-round completes
        # (mid-training from the job's perspective), the restarted job
        # resumes from the triple at --start-round
        from deeplearning4j_tpu import restore_model, write_model

        if args.resume_from:
            net = restore_model(args.resume_from)
        master = SyncAllReduceTrainingMaster(mesh=mesh)
        rep = replicated_sharding(mesh)
        for r in range(args.start_round, args.rounds):
            step = batches[r * n_devices : (r + 1) * n_devices]
            master.execute_training(net, ListDataSetIterator(step))
            if args.ckpt:
                # checkpointing a sharded job is COLLECTIVE: every rank
                # participates in the replicated fetch (a dead peer here
                # would wedge it — which is exactly why the crash below
                # happens after the round's checkpoint, like a worker dying
                # between checkpoints in production), then rank 0 serializes
                # host values and atomically replaces the per-round triple
                saved = net.params, net.opt_state, net.state
                net.params = jax.device_get(jax.device_put(net.params, rep))
                net.opt_state = jax.device_get(jax.device_put(net.opt_state, rep))
                if args.process_id == 0:
                    tmp = f"{args.ckpt}.tmp"
                    write_model(net, tmp)
                    os.replace(tmp, f"{args.ckpt}.r{r}.zip")
                net.params, net.opt_state, net.state = saved
            if args.process_id == args.crash_rank and r == args.crash_after_round:
                print(f"WORKER_CRASH pid={args.process_id} round={r}", flush=True)
                os._exit(17)  # simulated kill -9 mid-training
        master = None  # stats asserted only for the standard modes
    else:
        master = SyncAllReduceTrainingMaster(mesh=mesh)
    if master is not None:
        master.execute_training(net, ListDataSetIterator(batches))
        stats = master.get_stats().summary()
        assert stats.get("fit", 0) > 0, f"no fit phase recorded: {stats}"

    # Gather replicated host values (resharding collective on multi-process).
    rep = replicated_sharding(mesh)
    flat = {}
    for i, layer in enumerate(jax.device_put(net.params, rep)):
        for k, v in (layer or {}).items():
            flat[f"{i}_{k}"] = np.asarray(jax.device_get(v), dtype=np.float64)
    loss = float(net._last_loss)

    if args.process_id == 0:
        stem = f"{args.mode}{args.tag}_{args.num_processes}p"
        np.savez(os.path.join(args.out, f"params_{stem}.npz"), **flat)
        with open(os.path.join(args.out, f"meta_{stem}.json"), "w") as f:
            json.dump({"loss": loss, "devices": n_devices,
                       "process_count": jax.process_count()}, f)
    print(f"WORKER_OK pid={args.process_id} loss={loss:.6f}", flush=True)


if __name__ == "__main__":
    main()
