"""Resilience layer (ISSUE 14): typed retry/deadline/circuit policies, the
site registry behind /api/resilience, and deterministic fault injection —
FaultPlan scheduling, ChaosSource behavior, the replay-span contract, and
the seeded determinism guarantee (same seed → same fault sequence → same
recovery event trail).
"""

import os
import threading

import numpy as np
import pytest

from deeplearning4j_tpu import (
    DenseLayer,
    InputType,
    MultiLayerConfiguration,
    MultiLayerNetwork,
    OutputLayer,
    UpdaterConfig,
)
from deeplearning4j_tpu.runtime.checkpoint import CheckpointStore
from deeplearning4j_tpu.runtime.resilience import (
    CircuitBreaker,
    Deadline,
    DeadlinePolicy,
    RetryError,
    RetryPolicy,
    get_site,
    register_site,
    resilience_stats,
)
from deeplearning4j_tpu.streaming import QueueSource, ReplayBufferSource
from deeplearning4j_tpu.telemetry import MetricsRegistry
from deeplearning4j_tpu.telemetry.flight_recorder import (
    FlightRecorder,
    set_flight_recorder,
)
from deeplearning4j_tpu.testing.chaos import (
    CHAOS_PLAN_ENV,
    ChaosSource,
    FaultPlan,
    corrupt_file,
    truncate_file,
)
from deeplearning4j_tpu.tune.knobs import scoped_env

FEATURES, CLASSES = 12, 4


def _net(seed=3):
    return MultiLayerNetwork(MultiLayerConfiguration(
        layers=[DenseLayer(n_out=16, activation="tanh"),
                OutputLayer(n_out=CLASSES, activation="softmax",
                            loss="mcxent")],
        input_type=InputType.feed_forward(FEATURES),
        updater=UpdaterConfig(updater="adam", learning_rate=1e-2),
        seed=seed)).init()


def _policy(name, **kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("register", False)
    return RetryPolicy(name, **kw)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------------- retry policy

class TestRetryPolicy:
    def test_backoff_exponential_with_cap(self):
        p = _policy("t.backoff", base_s=0.5, cap_s=4.0, jitter=0.0)
        assert [p.backoff_s(n) for n in range(1, 6)] == [0.5, 1.0, 2.0, 4.0, 4.0]

    def test_jitter_deterministic_bounded_and_keyed(self):
        p = _policy("t.jitter", base_s=0.5, cap_s=8.0, jitter=0.5)
        # same (attempt, key) -> bit-identical; bounded in [raw, raw*(1+j)]
        for attempt, raw in ((1, 0.5), (2, 1.0), (3, 2.0)):
            a = p.backoff_s(attempt, key="w0")
            assert a == p.backoff_s(attempt, key="w0")
            assert raw <= a <= raw * 1.5
        # distinct keys stagger (the anti-thundering-herd property)
        waits = {p.backoff_s(1, key=f"w{i}") for i in range(4)}
        assert len(waits) == 4
        # and a freshly built policy with the same name reproduces them
        q = _policy("t.jitter", base_s=0.5, cap_s=8.0, jitter=0.5)
        assert q.backoff_s(1, key="w0") == p.backoff_s(1, key="w0")

    def test_run_retries_then_succeeds(self):
        p = _policy("t.run", max_attempts=5, base_s=0.001, cap_s=0.002)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        assert p.run(flaky) == "ok"
        s = p.stats()
        assert calls["n"] == 3
        assert s["retries_total"] == 2
        assert s["successes_total"] == 1
        assert s["giveups_total"] == 0
        assert s["consecutive_failures"] == 0

    def test_run_exhaustion_raises_retry_error(self):
        p = _policy("t.giveup", max_attempts=3, base_s=0.001, cap_s=0.002)
        with pytest.raises(RetryError) as ei:
            p.run(lambda: (_ for _ in ()).throw(ValueError("always")))
        assert ei.value.attempts == 3
        assert isinstance(ei.value.last, ValueError)
        s = p.stats()
        assert s["giveups_total"] == 1
        assert "always" in (s["last_error"] or "")

    def test_non_retryable_exception_passes_through(self):
        p = _policy("t.typed", max_attempts=5, base_s=0.001,
                    retry_on=(OSError,))
        calls = {"n": 0}

        def bad():
            calls["n"] += 1
            raise KeyError("not retryable")

        with pytest.raises(KeyError):
            p.run(bad)
        assert calls["n"] == 1  # no retries for a non-matching type

    def test_stop_event_aborts_retry_loop(self):
        p = _policy("t.stop", max_attempts=100, base_s=0.001)
        stop = threading.Event()
        stop.set()
        with pytest.raises(RetryError) as ei:
            p.run(lambda: (_ for _ in ()).throw(OSError("x")), stop=stop)
        assert ei.value.attempts == 1

    def test_expired_deadline_stops_retrying(self):
        p = _policy("t.deadline", max_attempts=100, base_s=0.001)
        dl = Deadline(0.0)
        with pytest.raises(RetryError):
            p.run(lambda: (_ for _ in ()).throw(OSError("x")), deadline=dl)

    def test_env_knobs_read_at_construction(self):
        with scoped_env(DL4JTPU_RETRY_MAX="2", DL4JTPU_RETRY_BASE_S="0.25",
                        DL4JTPU_RETRY_CAP_S="9.0", DL4JTPU_RETRY_JITTER="0"):
            p = _policy("t.env")
        assert p.max_attempts == 2
        assert p.base_s == 0.25
        assert p.cap_s == 9.0
        assert p.jitter == 0.0
        # explicit kwargs beat the env
        with scoped_env(DL4JTPU_RETRY_MAX="2"):
            q = _policy("t.env2", max_attempts=7)
        assert q.max_attempts == 7


# ----------------------------------------------------------------- deadline

class TestDeadline:
    def test_remaining_and_expired(self):
        clk = FakeClock()
        dl = Deadline(1.0, clock=clk)
        assert dl.remaining() == pytest.approx(1.0)
        assert not dl.expired
        clk.advance(1.5)
        assert dl.expired
        assert dl.remaining() == pytest.approx(-0.5)

    def test_pace_false_after_expiry_and_on_stop(self):
        dl = Deadline(0.2)
        assert dl.pace(0.01)  # plenty of budget left
        clk = FakeClock()
        expired = Deadline(0.1, clock=clk)
        clk.advance(0.2)
        assert not expired.pace(0.01)
        stop = threading.Event()
        stop.set()
        assert not Deadline(10.0).pace(0.01, stop=stop)

    def test_wait_event(self):
        fired = threading.Event()
        fired.set()
        assert Deadline(5.0).wait_event(fired)
        assert not Deadline(0.01).wait_event(threading.Event())

    def test_policy_counts_each_deadline_once(self):
        p = DeadlinePolicy("t.dl", 0.05, register=False)
        clk = FakeClock()
        d = p.start()
        d._clock = clk  # pin time for the test
        d._t0 = clk()
        clk.advance(0.1)
        assert not d.pace(0.01)
        assert not d.pace(0.01)  # already expired: not double counted
        s = p.stats()
        assert s["kind"] == "deadline"
        assert s["started_total"] == 1
        assert s["expired_total"] == 1

    def test_note_expired_explicit(self):
        p = DeadlinePolicy("t.dl2", 5.0, register=False)
        d = p.start()
        d.note_expired()  # e.g. the probe itself raised socket.timeout
        d.note_expired()
        assert p.stats()["expired_total"] == 1


# ---------------------------------------------------------- circuit breaker

class TestCircuitBreaker:
    def _cb(self, **kw):
        kw.setdefault("registry", MetricsRegistry())
        kw.setdefault("register", False)
        return CircuitBreaker("t.circuit", **kw)

    def test_opens_at_threshold_and_gates(self):
        clk = FakeClock()
        cb = self._cb(failure_threshold=3, cooldown_s=5.0, clock=clk)
        assert cb.allow() and cb.stats()["state"] == "closed"
        cb.record_failure()
        cb.record_failure()
        assert cb.stats()["state"] == "closed" and cb.allow()
        cb.record_failure()
        s = cb.stats()
        assert s["state"] == "open" and s["opens_total"] == 1
        assert not cb.allow()
        assert cb._m_state.value == 1
        assert 0.0 < s["cooldown_remaining_s"] <= 5.0

    def test_half_open_probe_closes_on_success(self):
        clk = FakeClock()
        cb = self._cb(failure_threshold=1, cooldown_s=5.0, clock=clk)
        cb.record_failure()
        assert not cb.allow()
        clk.advance(5.1)
        assert cb.allow()  # the probe gets through
        assert cb.stats()["state"] == "half-open"
        assert cb._m_state.value == 2
        cb.record_success()
        s = cb.stats()
        assert s["state"] == "closed" and s["failures"] == 0
        assert cb._m_state.value == 0

    def test_half_open_probe_failure_reopens(self):
        clk = FakeClock()
        cb = self._cb(failure_threshold=1, cooldown_s=5.0, clock=clk)
        cb.record_failure()
        clk.advance(5.1)
        assert cb.allow()
        cb.record_failure()
        s = cb.stats()
        assert s["state"] == "open" and s["opens_total"] == 2
        assert not cb.allow()

    def test_env_knobs(self):
        with scoped_env(DL4JTPU_CIRCUIT_FAILURES="2",
                        DL4JTPU_CIRCUIT_COOLDOWN_S="0.5"):
            cb = self._cb()
        assert cb.failure_threshold == 2
        assert cb.cooldown_s == 0.5


# ------------------------------------------------------------- site registry

class _DummySite:
    def __init__(self, name, payload):
        self.name = name
        self.payload = payload

    def stats(self):
        return dict(self.payload)


class TestSiteRegistry:
    def test_register_get_and_stats_snapshot(self):
        a = _DummySite("zz.test.a", {"kind": "dummy", "x": 1})
        b = _DummySite("zz.test.b", {"kind": "dummy", "x": 2})
        register_site(a)
        register_site(b)
        assert get_site("zz.test.a") is a
        sites = resilience_stats()["sites"]
        assert sites["zz.test.a"] == {"kind": "dummy", "x": 1}
        assert sites["zz.test.b"] == {"kind": "dummy", "x": 2}

    def test_last_registration_wins(self):
        register_site(_DummySite("zz.test.dup", {"gen": 1}))
        register_site(_DummySite("zz.test.dup", {"gen": 2}))
        assert resilience_stats()["sites"]["zz.test.dup"] == {"gen": 2}

    def test_production_policies_self_register(self, tmp_path):
        # building a CheckpointStore registers its IO retry site
        CheckpointStore(str(tmp_path), registry=MetricsRegistry())
        site = resilience_stats()["sites"].get("checkpoint.io")
        assert site is not None and site["kind"] == "retry"


# ------------------------------------------------------------ fault planning

class TestFaultPlan:
    def test_rejects_unknown_kind_and_missing_trigger(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultPlan(1, [{"site": "s", "fault": "meteor-strike", "at": [1]}])
        with pytest.raises(ValueError, match="'at' or 'every'"):
            FaultPlan(1, [{"site": "s", "fault": "nan-burst"}])

    def test_at_and_every_trigger_semantics(self):
        plan = FaultPlan(1, [
            {"site": "a", "fault": "nan-burst", "at": [2, 4]},
            {"site": "b", "fault": "source-error", "every": 3},
        ])
        hits_a = [n for n in range(1, 6) if plan.fire("a")]
        hits_b = [n for n in range(1, 8) if plan.fire("b")]
        assert hits_a == [2, 4]
        assert hits_b == [3, 6]
        assert plan.summary()["counts"] == {"a": 5, "b": 7}

    def test_same_seed_same_fired_sequence(self):
        spec = [{"site": "source.record", "fault": "nan-burst",
                 "at": [3, 7], "params": {"records": 4}}]
        trails = []
        for _ in range(2):
            plan = FaultPlan(42, spec)
            for _ in range(10):
                plan.fire("source.record")
            trails.append(plan.summary()["fired"])
        assert trails[0] == trails[1]
        assert [f["n"] for f in trails[0]] == [3, 7]
        assert all(f["records"] == 4 for f in trails[0])

    def test_env_round_trip(self):
        plan = FaultPlan(9, [{"site": "worker.healthz", "fault": "hang-worker",
                              "at": [1], "params": {"seconds": 2}}])
        back = FaultPlan.from_env({CHAOS_PLAN_ENV: plan.to_env()})
        assert back is not None
        assert back.seed == 9 and back.faults == plan.faults
        assert FaultPlan.from_env({}) is None
        assert FaultPlan.from_env({CHAOS_PLAN_ENV: "not json"}) is None

    def test_marker_makes_fault_at_most_once(self, tmp_path):
        marker = str(tmp_path / "fault.marker")
        spec = [{"site": "s", "fault": "hang-worker", "at": [1],
                 "marker": marker}]
        first = FaultPlan(1, spec)   # two plans, as two processes would see
        second = FaultPlan(1, spec)
        assert first.fire("s") is not None
        assert second.fire("s") is None  # marker already claimed
        assert os.path.exists(marker)

    def test_corrupt_checkpoint_executes_against_path(self, tmp_path):
        victim = tmp_path / "blob.bin"
        victim.write_bytes(b"\x42" * 4096)
        plan = FaultPlan(7, [{"site": "checkpoint.write",
                              "fault": "corrupt-checkpoint", "at": [1]}])
        fault = plan.fire("checkpoint.write", path=str(victim))
        assert fault is not None and fault["offsets"] > 0
        data = victim.read_bytes()
        assert len(data) == 4096 and any(b != 0x42 for b in data)

    def test_torn_tmp_drops_dead_writer_file(self, tmp_path):
        plan = FaultPlan(7, [{"site": "checkpoint.write", "fault": "torn-tmp",
                              "at": [1]}])
        fault = plan.fire("checkpoint.write", directory=str(tmp_path),
                          version=3)
        assert fault is not None
        assert os.path.exists(tmp_path / fault["tmp"])
        assert fault["tmp"].startswith(".tmp-v00000004-")

    def test_file_helpers(self, tmp_path):
        f = tmp_path / "x.bin"
        f.write_bytes(bytes(range(256)) * 4)
        offs = corrupt_file(str(f), seed=5, n_bytes=8)
        assert offs == corrupt_file(str(tmp_path / "x.bin"), seed=5, n_bytes=8) \
            or offs  # same seed+size -> same offsets (second call re-flips)
        assert truncate_file(str(f), keep_frac=0.25) == 256
        assert f.stat().st_size == 256


# -------------------------------------------------------------- chaos source

class TestChaosSource:
    def _queue(self, n):
        q = QueueSource(maxsize=64)
        for i in range(n):
            q.put(np.full(FEATURES, float(i), np.float32),
                  np.eye(CLASSES, dtype=np.float32)[i % CLASSES])
        return q

    def test_source_error_outage_then_recovers(self):
        plan = FaultPlan(1, [{"site": "source.poll", "fault": "source-error",
                              "at": [1], "params": {"polls": 2}}])
        src = ChaosSource(self._queue(3), plan)
        with pytest.raises(ConnectionError):
            src.poll(timeout=0.01)
        with pytest.raises(ConnectionError):
            src.poll(timeout=0.01)
        assert src.outages == 1
        rec = src.poll(timeout=0.01)
        assert rec is not None and rec[0][0] == 0.0

    def test_nan_burst_poisons_scheduled_records(self):
        plan = FaultPlan(1, [{"site": "source.record", "fault": "nan-burst",
                              "at": [3], "params": {"records": 2}}])
        src = ChaosSource(self._queue(6), plan)
        recs = [src.poll(timeout=0.01) for _ in range(6)]
        poisoned = [i for i, r in enumerate(recs) if np.isnan(r[0]).all()]
        assert poisoned == [2, 3]  # records 3 and 4, 1-based
        assert src.nan_records == 2
        # labels survive poisoning untouched
        assert recs[2][1] is not None and np.isfinite(recs[2][1]).all()

    def test_forwards_replay_contract_of_inner(self):
        plan = FaultPlan(1, [])
        src = ChaosSource(ReplayBufferSource(self._queue(3)), plan)
        for _ in range(3):
            assert src.poll(timeout=0.01) is not None
        assert src.replay_cursor() == 3
        assert len(src.replay(0, 3)) == 3


# -------------------------------------------------------------- replay spans

class TestReplaySpan:
    def test_span_is_start_exclusive_end_inclusive(self):
        q = QueueSource(maxsize=16)
        for i in range(5):
            q.put(np.full(FEATURES, float(i), np.float32))
        src = ReplayBufferSource(q)
        for _ in range(5):
            assert src.poll(timeout=0.01) is not None
        assert src.replay_cursor() == 5
        span = src.replay(2, 5)  # (2, 5] -> records 3..5 (values 2, 3, 4)
        assert [r[0][0] for r in span] == [2.0, 3.0, 4.0]
        assert src.replay(5, 5) == []

    def test_capacity_bounds_retention_best_effort(self):
        q = QueueSource(maxsize=16)
        for i in range(5):
            q.put(np.full(FEATURES, float(i), np.float32))
        src = ReplayBufferSource(q, capacity=3)
        for _ in range(5):
            src.poll(timeout=0.01)
        # aged-out records are simply absent from the span
        assert [r[0][0] for r in src.replay(0, 5)] == [2.0, 3.0, 4.0]

    def test_plain_source_has_no_replay_contract(self):
        q = QueueSource(maxsize=4)
        assert not hasattr(q, "replay_cursor")


# ------------------------------------------------- seeded determinism trail

class TestSeededDeterminism:
    """The acceptance guarantee: the same seed yields the same fault
    sequence AND the same recovery event trail (flight events compared
    field-wise, timestamps excluded, store paths normalized)."""

    SEED = 1405

    def _run_once(self, root):
        store_dir = os.path.join(root, "store")
        rec = FlightRecorder(dump_dir=os.path.join(root, "flight"),
                             registry=MetricsRegistry())
        set_flight_recorder(rec)
        try:
            plan = FaultPlan(self.SEED, [
                {"site": "checkpoint.write", "fault": "corrupt-checkpoint",
                 "at": [2]},
            ])
            store = CheckpointStore(store_dir, registry=MetricsRegistry(),
                                    chaos=plan)
            net = _net()
            store.save(net)
            store.save(net)  # corrupted by the plan as it lands
            model, info = store.restore_with_info()  # quarantine + fallback
            assert info.version == 1
            events = []
            for e in rec.events:
                clean = {}
                for k, v in e.items():
                    if k == "ts":
                        continue
                    if isinstance(v, str):
                        v = v.replace(store_dir, "<store>")
                    clean[k] = v
                events.append(clean)
            return plan.summary(), events
        finally:
            set_flight_recorder(None)

    def test_same_seed_same_faults_and_recovery_trail(self, tmp_path):
        sum_a, trail_a = self._run_once(str(tmp_path / "a"))
        sum_b, trail_b = self._run_once(str(tmp_path / "b"))
        assert sum_a == sum_b  # identical fault sequence, field-wise
        assert [f["fault"] for f in sum_a["fired"]] == ["corrupt-checkpoint"]
        assert trail_a == trail_b  # identical recovery event trail
        kinds = [e["kind"] for e in trail_a]
        assert "checkpoint_quarantined" in kinds
