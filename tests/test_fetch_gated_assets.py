"""Opportunistic egress probe (scripts/fetch_gated_assets.py): graceful on
a no-egress host, fetches + validates from any reachable mirror (reference:
MnistFetcher.java download path, TrainedModelHelper.java VGG16 download)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "fetch_gated_assets.py")


def _run(env_extra, home):
    env = dict(os.environ, HOME=str(home), DL4J_TPU_FETCH_TIMEOUT_S="3",
               **env_extra)
    r = subprocess.run([sys.executable, SCRIPT], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr  # opportunistic: ALWAYS exit 0
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_graceful_when_unreachable(tmp_path):
    out = _run({"DL4J_TPU_MNIST_URL": f"file://{tmp_path}/no-mirror",
                "DL4J_TPU_VGG16_URL": f"file://{tmp_path}/no-file.h5",
                "MNIST_DIR": str(tmp_path / "mnist")}, tmp_path)
    assert out["mnist"].startswith("unreachable")
    assert out["vgg16"].startswith("unreachable")
    assert not os.path.exists(tmp_path / ".dl4j-tpu" / "vgg16_weights.h5")


def test_vgg16_fetch_from_local_mirror(tmp_path):
    src = tmp_path / "weights.h5"
    # must clear the plausibility floor (> 1 MiB) as well as the signature
    src.write_bytes(b"\x89HDF\r\n\x1a\n" + b"\0" * (1 << 21))
    out = _run({"DL4J_TPU_MNIST_URL": f"file://{tmp_path}/no-mirror",
                "DL4J_TPU_VGG16_URL": f"file://{src}",
                "MNIST_DIR": str(tmp_path / "mnist")}, tmp_path)
    dest = tmp_path / ".dl4j-tpu" / "vgg16_weights.h5"
    assert out["vgg16"] == f"fetched:{dest}"
    assert dest.read_bytes().startswith(b"\x89HDF")


def test_vgg16_rejects_truncated_archive(tmp_path):
    src = tmp_path / "weights.h5"
    src.write_bytes(b"\x89HDF\r\n\x1a\n" + b"\0" * 64)  # valid sig, tiny
    out = _run({"DL4J_TPU_MNIST_URL": f"file://{tmp_path}/no-mirror",
                "DL4J_TPU_VGG16_URL": f"file://{src}",
                "MNIST_DIR": str(tmp_path / "mnist")}, tmp_path)
    assert out["vgg16"].startswith("unreachable (ValueError")


def test_vgg16_checksum_enforced_when_pinned(tmp_path):
    src = tmp_path / "weights.h5"
    src.write_bytes(b"\x89HDF\r\n\x1a\n" + b"\0" * (1 << 21))
    out = _run({"DL4J_TPU_MNIST_URL": f"file://{tmp_path}/no-mirror",
                "DL4J_TPU_VGG16_URL": f"file://{src}",
                "DL4J_TPU_VGG16_SHA256": "0" * 64,
                "MNIST_DIR": str(tmp_path / "mnist")}, tmp_path)
    assert out["vgg16"].startswith("unreachable (ValueError")
    assert not (tmp_path / ".dl4j-tpu" / "vgg16_weights.h5").exists()


def test_mnist_partial_fetch_leaves_no_new_archives(tmp_path, monkeypatch):
    """A fetch that dies partway must remove the files IT wrote (a half-set
    would un-skip the gated true-MNIST test onto synthetic data), while
    leaving pre-existing user files alone."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("fga", SCRIPT)
    fga = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fga)

    mnist_dir = tmp_path / "mnist"
    mnist_dir.mkdir()
    (mnist_dir / "user-note.txt").write_text("keep me")
    monkeypatch.setenv("MNIST_DIR", str(mnist_dir))

    def half_fetch(timeout_s):
        # first archive lands, then the connection dies
        (mnist_dir / "train-images-idx3-ubyte.gz").write_bytes(b"partial")
        raise OSError("connection reset")

    monkeypatch.setattr(fga, "fetch_mnist", half_fetch, raising=False)
    # try_mnist imports fetch_mnist at call time from the datasets module;
    # patch it there (the import inside the function resolves the module)
    import deeplearning4j_tpu.datasets.fetchers as fetchers

    monkeypatch.setattr(fetchers, "fetch_mnist", half_fetch)
    out = fga.try_mnist(timeout_s=2)
    assert out.startswith("unreachable (OSError")
    assert not (mnist_dir / "train-images-idx3-ubyte.gz").exists()
    assert (mnist_dir / "user-note.txt").exists()  # pre-existing untouched


def test_vgg16_rejects_non_hdf5(tmp_path):
    src = tmp_path / "weights.h5"
    src.write_bytes(b"<html>not a weights file</html>")
    out = _run({"DL4J_TPU_MNIST_URL": f"file://{tmp_path}/no-mirror",
                "DL4J_TPU_VGG16_URL": f"file://{src}",
                "MNIST_DIR": str(tmp_path / "mnist")}, tmp_path)
    assert out["vgg16"].startswith("unreachable (ValueError")
    # the partial download never lands at the destination
    base = tmp_path / ".dl4j-tpu"
    assert not (base / "vgg16_weights.h5").exists()
    assert not (base / "vgg16_weights.h5.part").exists()
