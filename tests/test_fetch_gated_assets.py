"""Opportunistic egress probe (scripts/fetch_gated_assets.py): graceful on
a no-egress host, fetches + validates from any reachable mirror (reference:
MnistFetcher.java download path, TrainedModelHelper.java VGG16 download)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "fetch_gated_assets.py")


def _run(env_extra, home):
    env = dict(os.environ, HOME=str(home), DL4J_TPU_FETCH_TIMEOUT_S="3",
               **env_extra)
    r = subprocess.run([sys.executable, SCRIPT], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr  # opportunistic: ALWAYS exit 0
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_graceful_when_unreachable(tmp_path):
    out = _run({"DL4J_TPU_MNIST_URL": f"file://{tmp_path}/no-mirror",
                "DL4J_TPU_VGG16_URL": f"file://{tmp_path}/no-file.h5",
                "MNIST_DIR": str(tmp_path / "mnist")}, tmp_path)
    assert out["mnist"].startswith("unreachable")
    assert out["vgg16"].startswith("unreachable")
    assert not os.path.exists(tmp_path / ".dl4j-tpu" / "vgg16_weights.h5")


def test_vgg16_fetch_from_local_mirror(tmp_path):
    src = tmp_path / "weights.h5"
    src.write_bytes(b"\x89HDF\r\n\x1a\n" + b"\0" * 64)
    out = _run({"DL4J_TPU_MNIST_URL": f"file://{tmp_path}/no-mirror",
                "DL4J_TPU_VGG16_URL": f"file://{src}",
                "MNIST_DIR": str(tmp_path / "mnist")}, tmp_path)
    dest = tmp_path / ".dl4j-tpu" / "vgg16_weights.h5"
    assert out["vgg16"] == f"fetched:{dest}"
    assert dest.read_bytes().startswith(b"\x89HDF")


def test_vgg16_rejects_non_hdf5(tmp_path):
    src = tmp_path / "weights.h5"
    src.write_bytes(b"<html>not a weights file</html>")
    out = _run({"DL4J_TPU_MNIST_URL": f"file://{tmp_path}/no-mirror",
                "DL4J_TPU_VGG16_URL": f"file://{src}",
                "MNIST_DIR": str(tmp_path / "mnist")}, tmp_path)
    assert out["vgg16"].startswith("unreachable (ValueError")
    # the partial download never lands at the destination
    base = tmp_path / ".dl4j-tpu"
    assert not (base / "vgg16_weights.h5").exists()
    assert not (base / "vgg16_weights.h5.part").exists()
