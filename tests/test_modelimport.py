"""Keras import tests (reference test strategy: modelimport HDF5 fixture
round-trips, SURVEY.md §4.5). Fixtures are written with h5py in exactly the
Keras 1.x save format — keras itself is not needed."""

import json

import h5py
import numpy as np
import pytest

from deeplearning4j_tpu.modelimport import (
    KerasImportError,
    import_keras_model_and_weights,
    import_keras_sequential_model_and_weights,
)
from deeplearning4j_tpu.modelimport.keras import (
    import_keras_model_config,
    import_keras_sequential_config,
)
from deeplearning4j_tpu.nn.layers.dense import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.layers.recurrent import GravesLSTM, LastTimeStepLayer
from deeplearning4j_tpu.utils.model_guesser import guess_model


def _write_keras_h5(path, model_config, training_config, layer_weights):
    """layer_weights: {layer_name: [(weight_name, array), ...]}"""
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(model_config).encode()
        if training_config is not None:
            f.attrs["training_config"] = json.dumps(training_config).encode()
        g = f.create_group("model_weights")
        g.attrs["layer_names"] = np.array(
            [n.encode() for n in layer_weights], dtype="S64"
        )
        for lname, weights in layer_weights.items():
            lg = g.create_group(lname)
            lg.attrs["weight_names"] = np.array(
                [wn.encode() for wn, _ in weights], dtype="S64"
            )
            for wn, arr in weights:
                lg.create_dataset(wn, data=arr)


def _dense_cfg(name, n_out, activation, input_shape=None):
    cfg = {"name": name, "output_dim": n_out, "activation": activation, "bias": True}
    if input_shape is not None:
        cfg["batch_input_shape"] = input_shape
    return {"class_name": "Dense", "config": cfg}


ADAM_TC = {
    "optimizer_config": {"class_name": "Adam", "config": {"lr": 0.002, "beta_1": 0.9}},
    "loss": "categorical_crossentropy",
}


def test_sequential_mlp_import_forward_matches_numpy(tmp_path):
    rng = np.random.default_rng(0)
    W1 = rng.normal(size=(5, 8)).astype(np.float32)
    b1 = rng.normal(size=(8,)).astype(np.float32)
    W2 = rng.normal(size=(8, 3)).astype(np.float32)
    b2 = rng.normal(size=(3,)).astype(np.float32)
    model_config = {
        "class_name": "Sequential",
        "config": [
            _dense_cfg("dense_1", 8, "relu", input_shape=[None, 5]),
            _dense_cfg("dense_2", 3, "softmax"),
        ],
    }
    path = str(tmp_path / "mlp.h5")
    _write_keras_h5(
        path,
        model_config,
        ADAM_TC,
        {
            "dense_1": [("dense_1_W", W1), ("dense_1_b", b1)],
            "dense_2": [("dense_2_W", W2), ("dense_2_b", b2)],
        },
    )

    net = import_keras_sequential_model_and_weights(path)
    assert isinstance(net.conf.layers[-1], OutputLayer)
    assert net.conf.layers[-1].loss == "mcxent"
    assert net.conf.updater.updater == "adam"
    assert net.conf.updater.learning_rate == pytest.approx(0.002)

    x = rng.normal(size=(4, 5)).astype(np.float32)
    h = np.maximum(x @ W1 + b1, 0.0)
    z = h @ W2 + b2
    expect = np.exp(z - z.max(-1, keepdims=True))
    expect /= expect.sum(-1, keepdims=True)
    out = np.asarray(net.output(x))
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)


def test_sequential_cnn_th_ordering_transposes_kernel(tmp_path):
    rng = np.random.default_rng(1)
    # keras 'th' conv weights: (out, in, kh, kw)
    Wc = rng.normal(size=(2, 1, 3, 3)).astype(np.float32)
    bc = np.zeros((2,), dtype=np.float32)
    Wd = rng.normal(size=(2 * 3 * 3, 4)).astype(np.float32)
    bd = np.zeros((4,), dtype=np.float32)
    model_config = {
        "class_name": "Sequential",
        "config": [
            {
                "class_name": "Convolution2D",
                "config": {
                    "name": "conv1", "nb_filter": 2, "nb_row": 3, "nb_col": 3,
                    "subsample": [1, 1], "border_mode": "valid",
                    "dim_ordering": "th", "activation": "relu",
                    "batch_input_shape": [None, 1, 8, 8], "bias": True,
                },
            },
            {
                "class_name": "MaxPooling2D",
                "config": {"name": "pool1", "pool_size": [2, 2], "strides": [2, 2],
                           "border_mode": "valid", "dim_ordering": "th"},
            },
            {"class_name": "Flatten", "config": {"name": "flatten_1"}},
            _dense_cfg("dense_1", 4, "softmax"),
        ],
    }
    path = str(tmp_path / "cnn.h5")
    _write_keras_h5(
        path,
        model_config,
        ADAM_TC,
        {
            "conv1": [("conv1_W", Wc), ("conv1_b", bc)],
            "pool1": [],
            "flatten_1": [],
            "dense_1": [("dense_1_W", Wd), ("dense_1_b", bd)],
        },
    )
    net = import_keras_sequential_model_and_weights(path)
    # HWIO kernel must equal the OIHW source transposed
    np.testing.assert_allclose(
        np.asarray(net.params[0]["W"]), np.transpose(Wc, (2, 3, 1, 0))
    )
    out = net.output(np.zeros((2, 8, 8, 1), dtype=np.float32))
    assert out.shape == (2, 4)
    np.testing.assert_allclose(np.asarray(out).sum(-1), 1.0, rtol=1e-5)


def test_lstm_import_gate_concatenation(tmp_path):
    rng = np.random.default_rng(2)
    n_in, H = 4, 3
    gates = {}
    for g in ("i", "c", "f", "o"):
        gates[f"W_{g}"] = rng.normal(size=(n_in, H)).astype(np.float32)
        gates[f"U_{g}"] = rng.normal(size=(H, H)).astype(np.float32)
        gates[f"b_{g}"] = rng.normal(size=(H,)).astype(np.float32)
    model_config = {
        "class_name": "Sequential",
        "config": [
            {
                "class_name": "LSTM",
                "config": {
                    "name": "lstm_1", "output_dim": H, "activation": "tanh",
                    "inner_activation": "hard_sigmoid",
                    "return_sequences": False,
                    "batch_input_shape": [None, 6, n_in],
                },
            },
            _dense_cfg("dense_1", 2, "softmax"),
        ],
    }
    path = str(tmp_path / "lstm.h5")
    _write_keras_h5(
        path,
        model_config,
        ADAM_TC,
        {
            "lstm_1": [(f"lstm_1_{k}", v) for k, v in gates.items()],
            "dense_1": [
                ("dense_1_W", rng.normal(size=(H, 2)).astype(np.float32)),
                ("dense_1_b", np.zeros(2, dtype=np.float32)),
            ],
        },
    )
    net = import_keras_sequential_model_and_weights(path)
    assert isinstance(net.conf.layers[0], GravesLSTM)
    assert isinstance(net.conf.layers[1], LastTimeStepLayer)
    W = np.asarray(net.params[0]["W"])
    # our gate column order [a(=keras c), f, o, i]
    np.testing.assert_allclose(W[:, 0:H], gates["W_c"])
    np.testing.assert_allclose(W[:, H : 2 * H], gates["W_f"])
    np.testing.assert_allclose(W[:, 2 * H : 3 * H], gates["W_o"])
    np.testing.assert_allclose(W[:, 3 * H :], gates["W_i"])
    np.testing.assert_allclose(np.asarray(net.params[0]["pF"]), 0.0)
    out = net.output(np.zeros((2, 6, n_in), dtype=np.float32))
    assert out.shape == (2, 2)


def test_batchnorm_running_stats_land_in_state(tmp_path):
    n = 5
    gamma = np.full(n, 2.0, np.float32)
    beta = np.full(n, -1.0, np.float32)
    mean = np.arange(n, dtype=np.float32)
    var = np.full(n, 4.0, np.float32)
    model_config = {
        "class_name": "Sequential",
        "config": [
            _dense_cfg("dense_1", n, "linear", input_shape=[None, n]),
            {
                "class_name": "BatchNormalization",
                "config": {"name": "bn_1", "epsilon": 1e-3, "mode": 0, "momentum": 0.9},
            },
        ],
    }
    path = str(tmp_path / "bn.h5")
    _write_keras_h5(
        path,
        model_config,
        None,
        {
            "dense_1": [
                ("dense_1_W", np.eye(n, dtype=np.float32)),
                ("dense_1_b", np.zeros(n, np.float32)),
            ],
            "bn_1": [
                ("bn_1_gamma", gamma),
                ("bn_1_beta", beta),
                ("bn_1_running_mean", mean),
                ("bn_1_running_std", var),
            ],
        },
    )
    net = import_keras_sequential_model_and_weights(path)
    np.testing.assert_allclose(np.asarray(net.params[1]["gamma"]), gamma)
    np.testing.assert_allclose(np.asarray(net.state[1]["mean"]), mean)
    np.testing.assert_allclose(np.asarray(net.state[1]["var"]), var)
    # inference uses the imported moving stats
    x = np.tile(mean, (3, 1)).astype(np.float32)
    out = np.asarray(net.output(x))
    np.testing.assert_allclose(out, np.tile(beta, (3, 1)), atol=1e-2)


def test_functional_model_with_merge(tmp_path):
    rng = np.random.default_rng(3)
    Wa = rng.normal(size=(4, 6)).astype(np.float32)
    Wb = rng.normal(size=(4, 6)).astype(np.float32)
    Wo = rng.normal(size=(6, 2)).astype(np.float32)
    mk = lambda n: np.zeros(n, np.float32)  # noqa: E731
    model_config = {
        "class_name": "Model",
        "config": {
            "layers": [
                {
                    "class_name": "InputLayer", "name": "input_1",
                    "config": {"name": "input_1", "batch_input_shape": [None, 4]},
                    "inbound_nodes": [],
                },
                {
                    "class_name": "Dense", "name": "branch_a",
                    "config": {"name": "branch_a", "output_dim": 6, "activation": "relu", "bias": True},
                    "inbound_nodes": [[["input_1", 0, 0]]],
                },
                {
                    "class_name": "Dense", "name": "branch_b",
                    "config": {"name": "branch_b", "output_dim": 6, "activation": "relu", "bias": True},
                    "inbound_nodes": [[["input_1", 0, 0]]],
                },
                {
                    "class_name": "Merge", "name": "merge_1",
                    "config": {"name": "merge_1", "mode": "sum"},
                    "inbound_nodes": [[["branch_a", 0, 0], ["branch_b", 0, 0]]],
                },
                {
                    "class_name": "Dense", "name": "out",
                    "config": {"name": "out", "output_dim": 2, "activation": "softmax", "bias": True},
                    "inbound_nodes": [[["merge_1", 0, 0]]],
                },
            ],
            "input_layers": [["input_1", 0, 0]],
            "output_layers": [["out", 0, 0]],
        },
    }
    path = str(tmp_path / "graph.h5")
    _write_keras_h5(
        path,
        model_config,
        None,
        {
            "branch_a": [("branch_a_W", Wa), ("branch_a_b", mk(6))],
            "branch_b": [("branch_b_W", Wb), ("branch_b_b", mk(6))],
            "out": [("out_W", Wo), ("out_b", mk(2))],
        },
    )
    net = import_keras_model_and_weights(path)
    x = rng.normal(size=(3, 4)).astype(np.float32)
    h = np.maximum(x @ Wa, 0) + np.maximum(x @ Wb, 0)
    z = h @ Wo
    expect = np.exp(z - z.max(-1, keepdims=True))
    expect /= expect.sum(-1, keepdims=True)
    out = np.asarray(net.output(x))
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_unsupported_layer_raises():
    with pytest.raises(KerasImportError):
        import_keras_sequential_config(
            {
                "class_name": "Sequential",
                "config": [{"class_name": "Lambda", "config": {"name": "l"}}],
            }
        )


def test_config_only_import_no_weights():
    conf, names = import_keras_sequential_config(
        {
            "class_name": "Sequential",
            "config": [
                _dense_cfg("d1", 16, "relu", input_shape=[None, 10]),
                {"class_name": "Dropout", "config": {"name": "do", "p": 0.25}},
                _dense_cfg("d2", 2, "softmax"),
            ],
        },
        ADAM_TC,
    )
    assert isinstance(conf.layers[0], DenseLayer)
    assert conf.layers[1].dropout == pytest.approx(0.25)
    assert isinstance(conf.layers[-1], OutputLayer)
    assert names[0] == "d1"


def test_model_guesser_roundtrip(tmp_path):
    # our own checkpoint zip
    from deeplearning4j_tpu import (
        InputType,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        OutputLayer as OL,
        UpdaterConfig,
    )
    from deeplearning4j_tpu.utils.serialization import write_model

    conf = MultiLayerConfiguration(
        layers=[OL(n_out=3, activation="softmax", loss="mcxent")],
        input_type=InputType.feed_forward(4),
        updater=UpdaterConfig(),
    )
    net = MultiLayerNetwork(conf).init()
    zpath = str(tmp_path / "model.zip")
    write_model(net, zpath)
    restored = guess_model(zpath)
    assert type(restored).__name__ == "MultiLayerNetwork"

    # conf json
    jpath = str(tmp_path / "conf.json")
    with open(jpath, "w") as f:
        f.write(conf.to_json())
    conf2 = guess_model(jpath)
    assert type(conf2).__name__ == "MultiLayerConfiguration"


def test_vgg16_configuration_shapes():
    from deeplearning4j_tpu.modelimport import vgg16_configuration

    conf = vgg16_configuration()
    types = conf.layer_input_types()
    # input to the first dense layer: 7x7x512 flattened
    dense_idx = len(conf.layers) - 3
    assert types[dense_idx].kind == "ff"
    assert types[dense_idx].size == 7 * 7 * 512
    assert conf.output_type().size == 1000


# ---------------------------------------------------------------------------
# channels-first flatten → Dense row-order parity (ADVICE round 1, high)
# ---------------------------------------------------------------------------


def _conv_chw_valid(x_chw, w_oihw, b):
    """Naive channels-first valid conv, stride 1 — the Keras/Theano reference."""
    o_n, _, kh, kw = w_oihw.shape
    h, w = x_chw.shape[1], x_chw.shape[2]
    out = np.zeros((o_n, h - kh + 1, w - kw + 1), np.float32)
    for o in range(o_n):
        for i in range(out.shape[1]):
            for j in range(out.shape[2]):
                out[o, i, j] = np.sum(w_oihw[o] * x_chw[:, i : i + kh, j : j + kw]) + b[o]
    return out


def test_th_conv_flatten_dense_numeric_parity(tmp_path):
    """Keras 1 'th' Conv→Flatten→Dense: the Dense kernel rows are in C,H,W
    flatten order; import must permute them to our NHWC (H,W,C) flatten order.
    Shapes coincide either way, so only a numeric check catches it."""
    rng = np.random.default_rng(3)
    C, H, W, O = 2, 5, 5, 3
    wc = rng.normal(size=(O, C, 2, 2)).astype(np.float32)  # OIHW ('th')
    bc = rng.normal(size=(O,)).astype(np.float32)
    n_flat = O * 4 * 4
    wd = rng.normal(size=(n_flat, 4)).astype(np.float32)
    bd = rng.normal(size=(4,)).astype(np.float32)

    model_config = {
        "class_name": "Sequential",
        "config": [
            {
                "class_name": "Convolution2D",
                "config": {
                    "name": "conv_1", "nb_filter": O, "nb_row": 2, "nb_col": 2,
                    "subsample": [1, 1], "border_mode": "valid",
                    "dim_ordering": "th", "activation": "relu", "bias": True,
                    "batch_input_shape": [None, C, H, W],
                },
            },
            {"class_name": "Flatten", "config": {"name": "flatten_1"}},
            _dense_cfg("dense_1", 4, "linear"),
        ],
    }
    path = str(tmp_path / "th_cnn.h5")
    _write_keras_h5(
        path, model_config, None,
        {
            "conv_1": [("conv_1_W", wc), ("conv_1_b", bc)],
            "flatten_1": [],
            "dense_1": [("dense_1_W", wd), ("dense_1_b", bd)],
        },
    )
    net = import_keras_sequential_model_and_weights(path)

    x_chw = rng.normal(size=(C, H, W)).astype(np.float32)
    # Keras/Theano reference: channels-first conv, relu, C-major flatten, dense
    ref = np.maximum(_conv_chw_valid(x_chw, wc, bc), 0.0).reshape(-1) @ wd + bd
    got = np.asarray(net.output(x_chw.transpose(1, 2, 0)[None]))[0]
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_keras2_channels_last_conv_kernel_not_transposed(tmp_path):
    """Keras 2 Conv2D channels_last: kernel is already HWIO and activations are
    channels-last — no transpose, no Dense-row permutation (ADVICE medium)."""
    rng = np.random.default_rng(4)
    C, H, W, O = 2, 5, 5, 3
    w_hwio = rng.normal(size=(2, 2, C, O)).astype(np.float32)
    bc = rng.normal(size=(O,)).astype(np.float32)
    n_flat = 4 * 4 * O
    wd = rng.normal(size=(n_flat, 4)).astype(np.float32)
    bd = rng.normal(size=(4,)).astype(np.float32)

    model_config = {
        "class_name": "Sequential",
        "config": {
            "layers": [
                {
                    "class_name": "Conv2D",
                    "config": {
                        "name": "conv_1", "filters": O, "kernel_size": [2, 2],
                        "strides": [1, 1], "padding": "valid",
                        "data_format": "channels_last", "activation": "relu",
                        "use_bias": True, "batch_input_shape": [None, H, W, C],
                    },
                },
                {"class_name": "Flatten", "config": {"name": "flatten_1"}},
                {"class_name": "Dense",
                 "config": {"name": "dense_1", "units": 4, "activation": "linear",
                            "use_bias": True}},
            ]
        },
    }
    path = str(tmp_path / "k2_cnn.h5")
    _write_keras_h5(
        path, model_config, None,
        {
            "conv_1": [("conv_1/kernel:0", w_hwio), ("conv_1/bias:0", bc)],
            "flatten_1": [],
            "dense_1": [("dense_1/kernel:0", wd), ("dense_1/bias:0", bd)],
        },
    )
    net = import_keras_sequential_model_and_weights(path)

    x_hwc = rng.normal(size=(H, W, C)).astype(np.float32)
    # channels-last reference: conv as OIHW over transposed input, then
    # channels-LAST flatten (H,W,C-major) — identical to our layout
    w_oihw = w_hwio.transpose(3, 2, 0, 1)
    conv = np.maximum(_conv_chw_valid(x_hwc.transpose(2, 0, 1), w_oihw, bc), 0.0)
    ref = conv.transpose(1, 2, 0).reshape(-1) @ wd + bd
    got = np.asarray(net.output(x_hwc[None]))[0]
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
