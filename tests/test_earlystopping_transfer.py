"""Early stopping + transfer learning + eval-extras tests (reference suites:
TestEarlyStopping.java, TransferLearning tests, EvalTest/ROC tests)."""

import math

import jax
import numpy as np
import pytest

from deeplearning4j_tpu import (
    DenseLayer,
    FineTuneConfiguration,
    FrozenLayer,
    InputType,
    MultiLayerConfiguration,
    MultiLayerNetwork,
    OutputLayer,
    ROC,
    ROCMultiClass,
    RegressionEvaluation,
    TransferLearning,
    UpdaterConfig,
)
from deeplearning4j_tpu.datasets.iterators import DataSet, ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    EarlyStoppingParallelTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.config import TerminationReason


def _net(lr=0.1, seed=3):
    conf = MultiLayerConfiguration(
        layers=[
            DenseLayer(n_out=16, activation="tanh"),
            OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.feed_forward(4),
        updater=UpdaterConfig(updater="sgd", learning_rate=lr),
        seed=seed,
    )
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    w = np.random.default_rng(42).normal(size=(4, 3))
    x = rng.normal(size=(n, 4))
    y = np.eye(3)[(x @ w).argmax(-1)]
    return DataSet(x, y)


class TestEarlyStopping:
    def test_max_epochs_termination(self):
        net = _net()
        train = _data()
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(5)],
            score_calculator=DataSetLossCalculator([_data(seed=1)]),
        )
        result = EarlyStoppingTrainer(cfg, net, [train]).fit()
        assert result.termination_reason == TerminationReason.EPOCH_TERMINATION_CONDITION
        assert result.total_epochs == 5
        assert result.best_model is not None
        assert result.best_model_score < math.inf
        assert len(result.score_vs_epoch) == 5

    def test_score_improvement_patience(self):
        net = _net(lr=0.0)  # lr=0 -> score never improves after epoch 0
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[
                MaxEpochsTerminationCondition(50),
                ScoreImprovementEpochTerminationCondition(patience=3),
            ],
            score_calculator=DataSetLossCalculator([_data(seed=1)]),
        )
        result = EarlyStoppingTrainer(cfg, net, [_data()]).fit()
        assert result.termination_reason == TerminationReason.EPOCH_TERMINATION_CONDITION
        assert "ScoreImprovement" in result.termination_details
        assert result.total_epochs <= 5

    def test_max_score_iteration_termination(self):
        net = _net(lr=1e4)  # diverges immediately
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(100)],
            iteration_termination_conditions=[
                MaxScoreIterationTerminationCondition(50.0),
                InvalidScoreIterationTerminationCondition(),
            ],
            score_calculator=DataSetLossCalculator([_data(seed=1)]),
        )
        result = EarlyStoppingTrainer(
            cfg, net, ListDataSetIterator([_data(seed=i) for i in range(8)], )
        ).fit()
        assert result.termination_reason == TerminationReason.ITERATION_TERMINATION_CONDITION
        assert result.total_epochs <= 3

    def test_max_time_termination(self):
        net = _net()
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(100000)],
            iteration_termination_conditions=[MaxTimeIterationTerminationCondition(0.0)],
            score_calculator=DataSetLossCalculator([_data(seed=1)]),
        )
        result = EarlyStoppingTrainer(cfg, net, [_data()]).fit()
        assert result.termination_reason == TerminationReason.ITERATION_TERMINATION_CONDITION

    def test_local_file_saver_roundtrip(self, tmp_path):
        net = _net()
        saver = LocalFileModelSaver(str(tmp_path))
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
            score_calculator=DataSetLossCalculator([_data(seed=1)]),
            model_saver=saver,
            save_last_model=True,
        )
        result = EarlyStoppingTrainer(cfg, net, [_data()]).fit()
        best = saver.get_best_model()
        assert best is not None
        assert saver.get_latest_model() is not None
        np.testing.assert_allclose(
            best.score(_data(seed=1)), result.best_model_score, rtol=1e-6
        )

    def test_parallel_early_stopping(self):
        net = _net(lr=0.2)
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
            score_calculator=DataSetLossCalculator([_data(seed=1)]),
        )
        batches = [_data(n=16, seed=i) for i in range(8)]
        result = EarlyStoppingParallelTrainer(
            cfg, net, ListDataSetIterator(batches), workers=4
        ).fit()
        assert result.total_epochs == 3
        assert result.best_model is not None


class TestTransferLearning:
    def test_freeze_feature_extractor(self):
        net = _net(lr=0.5)
        net.fit(_data())
        tl = (
            TransferLearning.Builder(net)
            .set_feature_extractor(0)
            .build()
        )
        assert isinstance(tl.conf.layers[0], FrozenLayer)
        frozen_before = np.asarray(tl.params[0]["W"]).copy()
        out_before = np.asarray(tl.params[1]["W"]).copy()
        tl.fit(_data(), epochs=3)
        np.testing.assert_array_equal(np.asarray(tl.params[0]["W"]), frozen_before)
        assert not np.allclose(np.asarray(tl.params[1]["W"]), out_before)

    def test_nout_replace(self):
        net = _net()
        tl = TransferLearning.Builder(net).n_out_replace(0, 32).build()
        assert tl.params[0]["W"].shape == (4, 32)
        assert tl.params[1]["W"].shape == (32, 3)
        tl.fit(_data())  # trains fine after surgery

    def test_remove_and_add_output_layer(self):
        net = _net()
        net.fit(_data())
        w0 = np.asarray(net.params[0]["W"])
        tl = (
            TransferLearning.Builder(net)
            .remove_output_layer()
            .add_layer(OutputLayer(n_in=16, n_out=5, activation="softmax", loss="mcxent"))
            .build()
        )
        assert tl.params[1]["W"].shape == (16, 5)
        np.testing.assert_array_equal(np.asarray(tl.params[0]["W"]), w0)  # kept
        x = _data().features
        assert tl.output(x).shape == (64, 5)

    def test_fine_tune_updater_override(self):
        net = _net()
        tl = (
            TransferLearning.Builder(net)
            .fine_tune_configuration(
                FineTuneConfiguration(updater=UpdaterConfig(updater="adam", learning_rate=1e-3))
            )
            .build()
        )
        assert tl.conf.updater.updater == "adam"
        tl.fit(_data())

    def test_frozen_json_roundtrip(self):
        net = _net()
        tl = TransferLearning.Builder(net).set_feature_extractor(0).build()
        conf2 = MultiLayerConfiguration.from_json(tl.conf.to_json())
        assert isinstance(conf2.layers[0], FrozenLayer)
        assert isinstance(conf2.layers[0].layer, DenseLayer)
        net2 = MultiLayerNetwork(conf2).init()
        x = _data().features
        assert net2.output(x).shape == (64, 3)


class TestROC:
    def test_perfect_classifier_auc_1(self):
        roc = ROC(threshold_steps=30)
        y = np.array([0, 0, 0, 1, 1, 1])
        p = np.array([0.1, 0.2, 0.3, 0.7, 0.8, 0.9])
        roc.eval(y, p)
        assert roc.calculate_auc() == pytest.approx(1.0, abs=0.02)

    def test_random_classifier_auc_half(self):
        rng = np.random.default_rng(0)
        roc = ROC(threshold_steps=50)
        y = rng.integers(0, 2, size=5000)
        p = rng.uniform(size=5000)
        roc.eval(y, p)
        assert roc.calculate_auc() == pytest.approx(0.5, abs=0.05)

    def test_two_column_input_and_accumulation(self):
        roc_a = ROC()
        y = np.eye(2)[np.array([0, 1, 1, 0])]
        p = np.array([[0.9, 0.1], [0.2, 0.8], [0.4, 0.6], [0.7, 0.3]])
        roc_a.eval(y, p)
        roc_b = ROC()
        roc_b.eval(y[:2], p[:2])
        roc_b.eval(y[2:], p[2:])
        assert roc_a.calculate_auc() == pytest.approx(roc_b.calculate_auc())
        assert roc_a.count == 4

    def test_multiclass(self):
        rng = np.random.default_rng(1)
        labels = np.eye(3)[rng.integers(0, 3, size=300)]
        # probabilities correlated with labels
        probs = labels * 0.6 + rng.uniform(size=(300, 3)) * 0.4
        probs /= probs.sum(-1, keepdims=True)
        roc = ROCMultiClass(threshold_steps=30)
        roc.eval(labels, probs)
        for c in range(3):
            assert roc.calculate_auc(c) > 0.8
        assert roc.calculate_average_auc() > 0.8


class TestRegressionEvaluation:
    def test_perfect_prediction(self):
        ev = RegressionEvaluation(["a", "b"])
        y = np.random.default_rng(0).normal(size=(50, 2))
        ev.eval(y, y)
        assert ev.mean_squared_error(0) == 0.0
        assert ev.mean_absolute_error(1) == 0.0
        assert ev.correlation_r2(0) == pytest.approx(1.0)

    def test_known_errors(self):
        ev = RegressionEvaluation()
        y = np.array([[0.0], [1.0], [2.0], [3.0]])
        p = y + np.array([[0.5], [-0.5], [0.5], [-0.5]])
        ev.eval(y, p)
        assert ev.mean_squared_error(0) == pytest.approx(0.25)
        assert ev.mean_absolute_error(0) == pytest.approx(0.5)
        assert ev.root_mean_squared_error(0) == pytest.approx(0.5)

    def test_accumulation_and_stats(self):
        rng = np.random.default_rng(2)
        y = rng.normal(size=(100, 3))
        p = y + 0.1 * rng.normal(size=(100, 3))
        ev1 = RegressionEvaluation(["x", "y", "z"])
        ev1.eval(y, p)
        ev2 = RegressionEvaluation(["x", "y", "z"])
        ev2.eval(y[:50], p[:50])
        ev2.eval(y[50:], p[50:])
        for c in range(3):
            assert ev1.mean_squared_error(c) == pytest.approx(ev2.mean_squared_error(c))
            assert ev1.correlation_r2(c) > 0.97
        assert "RMSE" in ev1.stats()

    def test_time_series_with_mask(self):
        ev = RegressionEvaluation()
        y = np.ones((2, 4, 1))
        p = np.zeros((2, 4, 1))
        mask = np.array([[1, 1, 0, 0], [1, 1, 1, 1]])
        ev.eval(y, p, mask=mask)
        assert ev._n == 6
        assert ev.mean_squared_error(0) == pytest.approx(1.0)


class TestTransferLearningGraph:
    """Round-1 missing #3: TransferLearning.GraphBuilder vertex surgery
    (reference: TransferLearning.java:420)."""

    def _trained_graph(self, rng):
        from deeplearning4j_tpu import ComputationGraphConfiguration, ComputationGraph

        conf = (
            ComputationGraphConfiguration.builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("d1", DenseLayer(n_out=16, activation="tanh"), "in")
            .add_layer("d2", DenseLayer(n_out=8, activation="tanh"), "d1")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax", loss="mcxent"), "d2")
            .set_outputs("out")
            .updater(UpdaterConfig(updater="adam", learning_rate=2e-2))
            .build()
        )
        net = ComputationGraph(conf).init()
        x = rng.normal(size=(64, 4))
        w = np.random.default_rng(9).normal(size=(4, 3))
        y = np.eye(3)[(x @ w).argmax(-1)]
        net.fit((x, y), epochs=30)
        return net, x, y

    def test_freeze_subgraph_and_replace_output_vertex(self, rng):
        from deeplearning4j_tpu import TransferLearning
        from deeplearning4j_tpu.nn.layers.frozen import FrozenLayer

        net, x, y = self._trained_graph(rng)
        d1_before = np.asarray(net.params["d1"]["W"])

        new_net = (
            TransferLearning.GraphBuilder(net)
            .fine_tune_configuration(
                FineTuneConfiguration(updater=UpdaterConfig(updater="sgd", learning_rate=0.1))
            )
            .set_feature_extractor("d2")  # freezes d2 AND its ancestor d1
            .remove_vertex_and_connections("out")
            .add_layer("new_out",
                       OutputLayer(n_out=5, activation="softmax", loss="mcxent"), "d2")
            .set_outputs("new_out")
            .build()
        )
        # frozen wrappers in place
        assert isinstance(new_net.conf.vertices["d1"].layer, FrozenLayer)
        assert isinstance(new_net.conf.vertices["d2"].layer, FrozenLayer)
        # feature-extractor params carried over, new head fresh with n_out=5
        np.testing.assert_array_equal(np.asarray(new_net.params["d1"]["W"]), d1_before)
        assert new_net.params["new_out"]["W"].shape == (8, 5)

        y5 = np.eye(5)[rng.integers(0, 5, size=64)]
        new_net.fit((x, y5), epochs=5)
        # frozen params unchanged by training; new head moved
        np.testing.assert_array_equal(np.asarray(new_net.params["d1"]["W"]), d1_before)
        out = new_net.output(x)
        assert out.shape == (64, 5)

    def test_n_out_replace_reinitializes_consumers(self, rng):
        from deeplearning4j_tpu import TransferLearning

        net, x, y = self._trained_graph(rng)
        d1_before = np.asarray(net.params["d1"]["W"])
        new_net = (
            TransferLearning.GraphBuilder(net)
            .n_out_replace("d2", 12)
            .build()
        )
        assert new_net.params["d2"]["W"].shape == (16, 12)
        assert new_net.params["out"]["W"].shape == (12, 3)
        np.testing.assert_array_equal(np.asarray(new_net.params["d1"]["W"]), d1_before)
        new_net.fit((x, y), epochs=2)  # still trains end-to-end

    def test_remove_vertex_keep_connections_rewires_by_name(self, rng):
        from deeplearning4j_tpu import TransferLearning

        net, x, y = self._trained_graph(rng)
        new_net = (
            TransferLearning.GraphBuilder(net)
            .remove_vertex_keep_connections("out")
            .add_layer("out", OutputLayer(n_out=7, activation="softmax", loss="mcxent"))
            .build()
        )
        assert new_net.params["out"]["W"].shape == (8, 7)
        assert np.asarray(new_net.output(x)).shape == (64, 7)

    def test_dangling_inputs_rejected(self, rng):
        from deeplearning4j_tpu import TransferLearning

        net, _, _ = self._trained_graph(rng)
        b = TransferLearning.GraphBuilder(net).remove_vertex_and_connections("d2")
        with pytest.raises(ValueError, match="not re-wired"):
            b.build()

    def test_surgery_preserves_batchnorm_running_stats(self, rng):
        """BN running mean/var must ride along with frozen params — a fresh
        0/1 state would silently change the extractor's inference outputs."""
        from deeplearning4j_tpu import (
            BatchNormalization, ComputationGraph, ComputationGraphConfiguration,
            TransferLearning,
        )

        conf = (
            ComputationGraphConfiguration.builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("d1", DenseLayer(n_out=8, activation="tanh"), "in")
            .add_layer("bn", BatchNormalization(), "d1")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax", loss="mcxent"), "bn")
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init()
        x = rng.normal(size=(64, 4)) * 3 + 1  # non-trivial stats
        y = np.eye(3)[rng.integers(0, 3, size=64)]
        net.fit((x, y), epochs=10)
        mean_before = np.asarray(net.state["bn"]["mean"])
        assert np.abs(mean_before).max() > 0.05  # stats actually moved

        new_net = (
            TransferLearning.GraphBuilder(net)
            .set_feature_extractor("bn")
            .remove_vertex_and_connections("out")
            .add_layer("head", OutputLayer(n_out=2, activation="softmax", loss="mcxent"), "bn")
            .set_outputs("head")
            .build()
        )
        np.testing.assert_array_equal(np.asarray(new_net.state["bn"]["mean"]), mean_before)

    def test_set_outputs_typo_rejected_at_build(self, rng):
        from deeplearning4j_tpu import TransferLearning

        net, _, _ = self._trained_graph(rng)
        b = TransferLearning.GraphBuilder(net).set_outputs("no_such_vertex")
        with pytest.raises(ValueError, match="not vertices"):
            b.build()
