"""DT5xx numerics lint: every shipped rule fires on a seeded violation and
stays silent on its clean twin; findings suppress via ``ignore=``; the CLI
``--numerics`` mode routes exit codes; scans are deterministic and
deduplicated; and every firing fixture is backed by *execution* ground
truth — the flagged program measurably degrades (NaN/inf or >1e-2 error
vs an f64 oracle) while the clean twin does not.

Fixture map (ISSUE 20 acceptance):
- DT500: bf16 dot_general with K>=32 and no f32 ``preferred_element_type``
  / clean twin passes ``preferred_element_type=float32``; also the generic
  ``lax.reduce``-with-add and ``cumsum`` accumulation forms
- DT501: bf16 scan carry across >= DT501_MIN_STEPS steps / clean twin
  carries f32
- DT502: parameter-lineage update arithmetic lands in bf16 under a
  declared f32 compute policy / clean twin updates in f32
- DT503: ``log``/``div``/``exp`` whose seeded input interval admits
  log(<=0), divide-through-zero, or exp overflow / clean twins clamp
  (``clip``/``maximum``) or bound the exponent
- DT504: softmax computed as exp(x)/sum(exp(x)) without subtracting the
  row max / clean twin uses ``jax.nn.softmax`` (structurally stabilized)
- DT505: net stores sub-f32 params with no ``conf.loss_scale`` declared /
  clean twin carries the PrecisionPolicy default scale

The loss-fix accuracy tests (satellite 1) prove the shipped fixes to the
unfused softmax-xent paths move the bf16 result toward the f64 oracle.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (
    DenseLayer,
    InputType,
    MultiLayerConfiguration,
    MultiLayerNetwork,
    OutputLayer,
    UpdaterConfig,
)
from deeplearning4j_tpu.analysis import RULES, merge_findings
from deeplearning4j_tpu.analysis.cli import main as cli_main
from deeplearning4j_tpu.analysis.numerics import (
    DT500_MIN_REDUCE,
    DT501_MIN_STEPS,
    check_jaxpr_numerics,
    check_network_numerics,
)
from deeplearning4j_tpu.nn import losses
from deeplearning4j_tpu.parallel.layout import PrecisionPolicy


def _shell(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _lint(fn, shells, **kw):
    closed = jax.make_jaxpr(fn)(*shells)
    findings, summary = check_jaxpr_numerics(closed, **kw)
    return {f.rule_id for f in findings}, findings, summary


def _mln(updater="adam"):
    return MultiLayerNetwork(MultiLayerConfiguration(
        layers=[DenseLayer(n_out=16, activation="relu"),
                OutputLayer(n_out=4, activation="softmax", loss="mcxent")],
        input_type=InputType.feed_forward(8),
        updater=UpdaterConfig(updater=updater, learning_rate=1e-3)))


# ------------------------------------------------------------- fixtures
# Each rule id maps to a (firing, clean) pair of (fn, shells, kwargs);
# the sweep test asserts the firing twin hits EXACTLY its rule and the
# clean twin hits nothing, so a fixture cannot silently drift onto a
# different DT5xx rule.

K = max(64, DT500_MIN_REDUCE * 2)
STEPS = DT501_MIN_STEPS * 2
BF, F32 = jnp.bfloat16, jnp.float32


def _dot_lo(x, w):
    return jnp.dot(x, w)


def _dot_hi(x, w):
    return jnp.dot(x, w, preferred_element_type=jnp.float32)


def _reduce_lo(x):
    return jax.lax.reduce(x, jnp.asarray(0, x.dtype),
                          jax.lax.add, dimensions=(0,))


def _cumsum_lo(x):
    return jnp.cumsum(x)


def _scan(dtype):
    def fn(c0, xs):
        def body(c, x):
            return c * jnp.asarray(0.5, c.dtype) + x, c
        return jax.lax.scan(body, c0, xs)
    return fn


def _upd(p, g):
    return p - jnp.asarray(0.01, p.dtype) * g


_FIXTURES = {
    "DT500": (
        (_dot_lo, [_shell((8, K), BF), _shell((K, 8), BF)], {}),
        (_dot_hi, [_shell((8, K), BF), _shell((K, 8), BF)], {}),
    ),
    "DT501": (
        (_scan(BF), [_shell((), BF), _shell((STEPS,), BF)], {}),
        (_scan(F32), [_shell((), F32), _shell((STEPS,), F32)], {}),
    ),
    "DT502": (
        (_upd, [_shell((8,), BF), _shell((8,), BF)],
         dict(in_lineage=["param", None], compute_dtype="float32")),
        (_upd, [_shell((8,), F32), _shell((8,), F32)],
         dict(in_lineage=["param", None], compute_dtype="float32")),
    ),
    "DT503": (
        (lambda x: jnp.log(x), [_shell((8,), F32)],
         dict(in_ranges=[(-1.0, 1.0)])),
        (lambda x: jnp.log(jnp.clip(x, 1e-7, 1.0)), [_shell((8,), F32)],
         dict(in_ranges=[(-1.0, 1.0)])),
    ),
    "DT504": (
        (lambda x: (lambda e: e / jnp.sum(e, -1, keepdims=True))(jnp.exp(x)),
         [_shell((4, 8), F32)], dict(in_ranges=[(-1e3, 1e3)])),
        (lambda x: jax.nn.softmax(x, axis=-1),
         [_shell((4, 8), F32)], dict(in_ranges=[(-1e3, 1e3)])),
    ),
    # DT505 is net-level (params + conf, not one jaxpr) — tested below.
}


class TestFiringAndClean:
    @pytest.mark.parametrize("rule", sorted(_FIXTURES))
    def test_firing_fixture_hits_exactly_its_rule(self, rule):
        fn, shells, kw = _FIXTURES[rule][0]
        ids, findings, _ = _lint(fn, shells, **kw)
        assert ids == {rule}, f"{rule} firing fixture hit {ids}"
        assert all(f.rule_id in RULES for f in findings)

    @pytest.mark.parametrize("rule", sorted(_FIXTURES))
    def test_clean_twin_is_silent(self, rule):
        fn, shells, kw = _FIXTURES[rule][1]
        ids, _, _ = _lint(fn, shells, **kw)
        assert ids == set(), f"{rule} clean twin hit {ids}"

    def test_dt500_generic_reduce_and_cumsum(self):
        ids, _, _ = _lint(_reduce_lo, [_shell((K,), BF)])
        assert ids == {"DT500"}
        ids, _, _ = _lint(_cumsum_lo, [_shell((K,), BF)])
        assert ids == {"DT500"}
        # f32 accumulation of the same programs is clean
        ids, _, _ = _lint(_reduce_lo, [_shell((K,), F32)])
        assert ids == set()
        ids, _, _ = _lint(_cumsum_lo, [_shell((K,), F32)])
        assert ids == set()

    def test_dt503_div_and_exp_forms(self):
        ids, _, _ = _lint(lambda a, b: a / b,
                          [_shell((8,), F32)] * 2,
                          in_ranges=[(0.0, 1.0), (-1.0, 1.0)])
        assert ids == {"DT503"}
        ids, _, _ = _lint(lambda a, b: a / jnp.maximum(b, 1e-6),
                          [_shell((8,), F32)] * 2,
                          in_ranges=[(0.0, 1.0), (-1.0, 1.0)])
        assert ids == set()
        ids, _, _ = _lint(lambda x: jnp.exp(x), [_shell((8,), F32)],
                          in_ranges=[(-1e3, 1e3)])
        assert ids == {"DT503"}
        ids, _, _ = _lint(lambda x: jnp.exp(jnp.clip(x, -30.0, 30.0)),
                          [_shell((8,), F32)], in_ranges=[(-1e3, 1e3)])
        assert ids == set()

    def test_dt501_short_trip_is_exempt(self):
        fn = _scan(BF)
        short = DT501_MIN_STEPS - 1
        ids, _, _ = _lint(fn, [_shell((), BF), _shell((short,), BF)])
        assert "DT501" not in ids

    def test_dt505_net_level_firing_and_clean(self):
        net = _mln().init()
        PrecisionPolicy(params_dtype="bfloat16").apply_to_net(net)
        # clean: the policy stamped its power-of-two default scale
        assert net.conf.loss_scale == PrecisionPolicy.DEFAULT_LOSS_SCALE
        rep = check_network_numerics(net)
        assert "DT505" not in {f.rule_id for f in rep["findings"]}
        # firing: same storage dtype, scale knob cleared
        net.conf.loss_scale = None
        net._train_step = None
        rep = check_network_numerics(net)
        ids = {f.rule_id for f in rep["findings"]}
        assert "DT505" in ids
        # the f32 update island keeps the rest of the step clean even here
        assert ids == {"DT505"}
        dt505 = [f for f in rep["findings"] if f.rule_id == "DT505"]
        assert dt505[0].severity == "info"
        assert "loss_scale" in dt505[0].hint


class TestRegistrySweep:
    def test_numerics_scope_is_exactly_dt500_to_dt505(self):
        scoped = {rid for rid, r in RULES.items() if r.scope == "numerics"}
        assert scoped == {"DT500", "DT501", "DT502", "DT503", "DT504",
                          "DT505"}

    def test_every_jaxpr_rule_has_a_fixture_pair(self):
        jaxpr_rules = {rid for rid, r in RULES.items()
                       if r.scope == "numerics"} - {"DT505"}
        assert set(_FIXTURES) == jaxpr_rules
        for rid, (firing, clean) in _FIXTURES.items():
            assert firing[0] is not clean[0] or firing[1] != clean[1]

    def test_rule_metadata_complete(self):
        for rid in ("DT500", "DT501", "DT502", "DT503", "DT504", "DT505"):
            r = RULES[rid]
            assert r.title and r.hint
            assert r.severity in ("info", "warning", "error")


class TestSuppressionAndCli:
    def test_ignore_drops_rule(self):
        fn, shells, kw = _FIXTURES["DT503"][0]
        ids, _, _ = _lint(fn, shells, ignore=("DT503",), **kw)
        assert ids == set()

    def test_analyze_ir_ignore_passthrough(self):
        net = _mln().init()
        PrecisionPolicy(params_dtype="bfloat16").apply_to_net(net)
        net.conf.loss_scale = None
        net._train_step = None
        rep = net.analyze_ir(8, ignore=("DT505",))
        assert "DT505" not in {f.rule_id for f in rep["findings"]}

    def test_cli_numerics_exit_codes(self, tmp_path, capsys):
        conf = _mln().conf
        conf.params_dtype = "bfloat16"
        conf.loss_scale = None
        firing = tmp_path / "firing.json"
        firing.write_text(conf.to_json())
        conf.loss_scale = 4096.0
        clean = tmp_path / "clean.json"
        clean.write_text(conf.to_json())

        # DT505 is info severity: trips --fail-on info, not warning
        rc = cli_main([str(firing), "--numerics", "--fail-on", "info",
                       "--json"])
        rep = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert "DT505" in {f["rule_id"] for f in rep["findings"]}
        assert rep["static_cost"][0]["numerics"]["rules"].get("DT505") == 1

        rc = cli_main([str(firing), "--numerics", "--fail-on", "warning"])
        capsys.readouterr()
        assert rc == 0

        rc = cli_main([str(clean), "--numerics", "--fail-on", "info",
                       "--json"])
        rep = json.loads(capsys.readouterr().out)
        assert "DT505" not in {f["rule_id"] for f in rep["findings"]}

    def test_cli_ir_and_numerics_compose(self, tmp_path, capsys):
        p = tmp_path / "conf.json"
        p.write_text(_mln().conf.to_json())
        rc = cli_main([str(p), "--ir", "--numerics", "--fail-on", "warning",
                       "--json"])
        rep = json.loads(capsys.readouterr().out)
        assert rc == 0
        cost = rep["static_cost"][0]
        assert "numerics" in cost and "flops" in cost  # one shared trace


class TestDeterminism:
    def test_same_program_same_findings(self):
        fn, shells, kw = _FIXTURES["DT504"][0]
        _, a, _ = _lint(fn, shells, **kw)
        _, b, _ = _lint(fn, shells, **kw)
        assert [f.to_dict() for f in a] == [f.to_dict() for f in b]

    def test_findings_dedupe_and_aggregate(self):
        def twice(x):
            return jnp.log(x) + jnp.log(x * jnp.asarray(2.0, x.dtype))
        _, findings, _ = _lint(twice, [_shell((8,), F32)],
                               in_ranges=[(-1.0, 1.0)])
        # two hazardous log sites aggregate into ONE DT503 finding with a
        # site count, and merging is idempotent
        assert len([f for f in findings if f.rule_id == "DT503"]) == 1
        assert "2 site(s)" in findings[0].message
        assert merge_findings(list(findings) + list(findings)) == \
            merge_findings(findings)


class TestGroundTruth:
    """Satellite: every flagged fixture EXECUTES worse than its twin —
    NaN/inf or >1e-2 error against an f64 oracle, on CPU,
    deterministically."""

    def test_dt500_low_precision_accumulation_overflows(self):
        x = jnp.full((16, 2048), 16.0, jnp.float16)
        w = jnp.full((2048, 16), 16.0, jnp.float16)
        flagged = jnp.dot(x, w)  # true sum 524288 > f16 max 65504
        clean = jnp.dot(x, w, preferred_element_type=jnp.float32)
        assert bool(jnp.isinf(flagged).all())
        assert float(clean[0, 0]) == 16.0 * 16.0 * 2048
        # and the lint agrees with the execution evidence
        ids, _, _ = _lint(lambda a, b: jnp.dot(a, b),
                          [_shell((16, 2048), jnp.float16)] * 0 +
                          [_shell((16, 2048), jnp.float16),
                           _shell((2048, 16), jnp.float16)])
        assert "DT500" in ids

    def test_dt501_low_precision_carry_stalls(self):
        def body(c, _):
            return c + jnp.asarray(1e-3, c.dtype), None

        def run(dtype):
            c, _ = jax.lax.scan(body, jnp.asarray(1.0, dtype), None,
                                length=1000)
            return float(c)

        oracle = 1.0 + 1e-3 * 1000  # exact in f64
        flagged, clean = run(jnp.bfloat16), run(jnp.float32)
        assert abs(flagged - oracle) / oracle > 1e-2  # stalls at 1.0
        assert abs(clean - oracle) / oracle < 1e-3
        ids, _, _ = _lint(
            lambda c0: jax.lax.scan(body, c0, None, length=1000)[0],
            [_shell((), BF)])
        assert "DT501" in ids

    def test_dt502_low_precision_updates_vanish(self):
        def train(dtype, steps=256):
            p = jnp.asarray(1.0, dtype)
            upd = jnp.asarray(1e-3, dtype)
            for _ in range(steps):
                p = p + upd
            return float(p)

        oracle = 1.0 + 1e-3 * 256
        flagged, clean = train(jnp.bfloat16), train(jnp.float32)
        assert abs(flagged - oracle) / oracle > 1e-2  # 1e-3 < bf16 ulp at 1
        assert abs(clean - oracle) / oracle < 1e-3
        ids, _, _ = _lint(_upd, [_shell((8,), BF), _shell((8,), BF)],
                          in_lineage=["param", None],
                          compute_dtype="float32")
        assert "DT502" in ids

    def test_dt503_log_and_div_produce_nonfinite(self):
        x = jnp.asarray([0.0, 0.5], jnp.float32)
        flagged = jnp.log(x)
        clean = jnp.log(jnp.clip(x, 1e-7, 1.0))
        assert not bool(jnp.isfinite(flagged).all())
        assert bool(jnp.isfinite(clean).all())
        den = jnp.asarray([0.0, 2.0], jnp.float32)
        flagged = jnp.asarray(1.0) / den
        clean = jnp.asarray(1.0) / jnp.maximum(den, 1e-6)
        assert not bool(jnp.isfinite(flagged).all())
        assert bool(jnp.isfinite(clean).all())

    def test_dt504_naive_softmax_overflows(self):
        logits = jnp.asarray([100.0, 0.0, -50.0], jnp.float32)
        flagged = jnp.exp(logits) / jnp.sum(jnp.exp(logits))
        clean = jax.nn.softmax(logits)
        assert not bool(jnp.isfinite(flagged).all())
        oracle = np.exp(np.asarray(logits, np.float64) - 100.0)
        oracle /= oracle.sum()
        assert np.allclose(np.asarray(clean, np.float64), oracle, atol=1e-6)

    def test_dt505_unscaled_tiny_grads_flush_scaled_survive(self):
        g, S = 1e-8, 4096.0  # g below f16's smallest denormal ~5.96e-8
        flagged = float(jnp.asarray(g, jnp.float32).astype(jnp.float16))
        scaled = float((jnp.asarray(g, jnp.float32) * S)
                       .astype(jnp.float16).astype(jnp.float32) / S)
        assert flagged == 0.0  # 100% error: the gradient is gone
        assert abs(scaled - g) / g < 1e-2


class TestLossFixAccuracy:
    """Satellite: the shipped fixes to the unfused loss paths move the
    bf16 result toward the f64 oracle (before/after on the same inputs)."""

    @staticmethod
    def _oracle_rows(pre, lab):
        p = np.asarray(pre, np.float64)
        l = np.asarray(lab, np.float64)
        m = p.max(-1, keepdims=True)
        logp = (p - m) - np.log(np.exp(p - m).sum(-1, keepdims=True))
        return -(l * logp).sum(-1)

    def test_softmax_xent_rows_bf16_toward_oracle(self):
        from deeplearning4j_tpu import ops

        rng = np.random.RandomState(7)
        pre = jnp.asarray(rng.randn(32, 64) * 8, jnp.bfloat16)
        lab = jax.nn.one_hot(jnp.asarray(rng.randint(0, 64, 32)), 64,
                             dtype=jnp.bfloat16)
        oracle = self._oracle_rows(pre, lab)
        # "before": the pre-fix unfused formula at data precision
        before = -jnp.sum(lab * jax.nn.log_softmax(pre, axis=-1), axis=-1)
        after = ops.softmax_xent_rows(lab, pre)
        err_before = np.abs(np.asarray(before, np.float64) - oracle).max()
        err_after = np.abs(np.asarray(after, np.float64) - oracle).max()
        assert err_after < err_before
        assert err_after < 1e-4
        # parity with the fused kernel's output contract: promoted dtype
        assert after.dtype == jnp.float32

    def test_mcxent_nd_fallback_bf16_toward_oracle(self):
        rng = np.random.RandomState(11)
        pre = jnp.asarray(rng.randn(4, 6, 64) * 8, jnp.bfloat16)
        lab = jax.nn.one_hot(jnp.asarray(rng.randint(0, 64, (4, 6))), 64,
                             dtype=jnp.bfloat16)
        p = np.asarray(pre, np.float64)
        l = np.asarray(lab, np.float64)
        m = p.max(-1, keepdims=True)
        logp = (p - m) - np.log(np.exp(p - m).sum(-1, keepdims=True))
        oracle = float((-(l * logp)).sum(-1).reshape(4, -1).sum(-1).mean())
        before = float(jnp.mean(jnp.sum(
            (-(lab * jax.nn.log_softmax(pre, -1))).reshape(4, -1), -1)))
        after = float(losses.mcxent(lab, pre, "softmax"))
        assert abs(after - oracle) < abs(before - oracle)
        assert abs(after - oracle) < 1e-3 * max(1.0, abs(oracle))

    def test_msle_negative_labels_finite(self):
        labels = jnp.asarray([[-2.0, 0.5]], jnp.float32)
        preds = jnp.asarray([[0.5, 0.5]], jnp.float32)
        out = losses.msle(labels, preds, "identity")
        assert bool(jnp.isfinite(out))  # pre-fix: log1p(-2) -> nan
