"""Test harness config.

Forces the CPU backend with 8 virtual devices — the analog of the reference's
Spark `local[n]` test trick (SURVEY.md §4.3): multi-device mesh semantics
(sharding, collectives, averaging) are exercised in one process without TPU
hardware. Must run before any jax backend is initialized (jax itself is
already pre-imported by the axon sitecustomize; see below).

Also enables x64 so gradient checks (tests/test_gradcheck.py) run in float64,
matching the reference's double-precision GradientCheckUtil runs.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Force-override: the environment pins JAX_PLATFORMS=axon (the real TPU tunnel)
# and sitecustomize PRE-IMPORTS jax at interpreter startup, so env vars set here
# are latched too late. One audited implementation of the recipe lives in
# __graft_entry__._force_cpu_mesh (fails loudly if a backend beat us to init).
from __graft_entry__ import _force_cpu_mesh

_force_cpu_mesh(8)

import jax

jax.config.update("jax_enable_x64", True)
# Persistent compilation cache: repeated test runs skip XLA recompiles.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running soak/chaos tests, excluded from tier-1 "
        "(pytest -m 'not slow')")


@pytest.fixture
def rng():
    return np.random.default_rng(12345)


@pytest.fixture
def tiny_classification(rng):
    """Linearly-separable-ish 3-class problem (Iris-shaped: 4 features)."""
    n, f, c = 96, 4, 3
    x = rng.normal(size=(n, f)).astype(np.float64)
    w = rng.normal(size=(f, c))
    y_idx = (x @ w + 0.1 * rng.normal(size=(n, c))).argmax(-1)
    y = np.eye(c)[y_idx]
    return x, y
