"""Mixture-of-Experts layer + expert parallelism tests (the EP axis of the
driver's tp/pp/dp/sp/ep sharding matrix; no reference counterpart — 2016)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (
    InputType,
    MixtureOfExpertsLayer,
    MultiLayerConfiguration,
    MultiLayerNetwork,
    OutputLayer,
    UpdaterConfig,
)
from deeplearning4j_tpu.datasets.iterators import DataSet, ListDataSetIterator


def _layer(**kw):
    defaults = dict(n_out=8, n_experts=4, hidden=16, top_k=1,
                    capacity_factor=2.0, residual=False)
    defaults.update(kw)
    return MixtureOfExpertsLayer(**defaults)


class TestRouting:
    def _apply(self, layer, x, seed=0):
        it = InputType.feed_forward(x.shape[-1])
        params = layer.init_params(jax.random.PRNGKey(seed), it)
        out, _ = layer.apply(params, jnp.asarray(x), {})
        return params, np.asarray(out)

    def test_top1_matches_manual_expert_ffn(self):
        """With ample capacity, each token's output == its argmax expert's
        FFN weighted by the gate probability."""
        layer = _layer()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 8)).astype(np.float32)
        params, out = self._apply(layer, x)

        probs = jax.nn.softmax(x @ np.asarray(params["Wg"]), axis=-1)
        idx = np.argmax(probs, axis=-1)
        for i in range(len(x)):
            e = idx[i]
            h = np.maximum(x[i] @ np.asarray(params["W1"][e])
                           + np.asarray(params["b1"][e]), 0.0)
            expect = (h @ np.asarray(params["W2"][e])
                      + np.asarray(params["b2"][e])) * probs[i, e]
            np.testing.assert_allclose(out[i], expect, rtol=1e-4, atol=1e-5)

    def test_top2_combines_two_experts(self):
        layer = _layer(top_k=2)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(8, 8)).astype(np.float32)
        params, out = self._apply(layer, x)
        probs = jax.nn.softmax(x @ np.asarray(params["Wg"]), axis=-1)
        order = np.argsort(-probs, axis=-1)
        for i in range(len(x)):
            expect = np.zeros(8, np.float32)
            for e in order[i, :2]:
                h = np.maximum(x[i] @ np.asarray(params["W1"][e])
                               + np.asarray(params["b1"][e]), 0.0)
                expect += (h @ np.asarray(params["W2"][e])
                           + np.asarray(params["b2"][e])) * probs[i, e]
            np.testing.assert_allclose(out[i], expect, rtol=1e-4, atol=1e-5)

    def test_capacity_drops_overflow_residual_carries(self):
        """Tokens past capacity contribute zero MoE output; with residual=True
        the token representation still flows."""
        layer = _layer(capacity_factor=0.25, residual=True, n_out=8)
        rng = np.random.default_rng(2)
        x = rng.normal(size=(16, 8)).astype(np.float32)
        params, out = self._apply(layer, x)
        # capacity = 0.25 * 16 / 4 = 1 token per expert: most tokens dropped,
        # dropped rows equal the residual input exactly
        dropped = np.isclose(out, x, atol=1e-6).all(axis=-1)
        assert dropped.sum() >= 16 - 4 * 1 - 1

    def test_sequence_input_and_json_roundtrip(self):
        conf = MultiLayerConfiguration(
            layers=[_layer(residual=False, n_out=8),
                    OutputLayer(n_out=3, activation="softmax")],
            input_type=InputType.feed_forward(8),
            updater=UpdaterConfig(updater="adam", learning_rate=1e-3),
        )
        restored = MultiLayerConfiguration.from_json(conf.to_json())
        l0 = restored.layers[0]
        assert isinstance(l0, MixtureOfExpertsLayer)
        assert l0.n_experts == 4 and l0.capacity_factor == 2.0

    def test_residual_requires_matching_width(self):
        layer = _layer(residual=True, n_out=6)
        with pytest.raises(ValueError, match="n_in == n_out"):
            layer.init_params(jax.random.PRNGKey(0), InputType.feed_forward(8))

    def test_load_balance_stats(self):
        layer = _layer()
        rng = np.random.default_rng(3)
        x = rng.normal(size=(32, 8)).astype(np.float32)
        params = layer.init_params(jax.random.PRNGKey(0), InputType.feed_forward(8))
        stats = layer.load_balance_stats(params, x)
        np.testing.assert_allclose(np.asarray(stats["expert_fraction"]).sum(), 1.0,
                                   rtol=1e-6)
        assert stats["capacity"] == 16


class TestTrainingAndEP:
    def _conf(self):
        return MultiLayerConfiguration(
            layers=[
                MixtureOfExpertsLayer(n_out=8, n_experts=4, hidden=16,
                                      capacity_factor=2.0, residual=True),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
            ],
            input_type=InputType.feed_forward(8),
            updater=UpdaterConfig(updater="adam", learning_rate=5e-3),
            seed=0,
        )

    def _batches(self, n, batch=16, seed=0):
        rng = np.random.default_rng(seed)
        w = np.random.default_rng(9).normal(size=(8, 3))
        out = []
        for _ in range(n):
            x = rng.normal(size=(batch, 8)).astype(np.float32)
            out.append(DataSet(x, np.eye(3, dtype=np.float32)[(x @ w).argmax(-1)]))
        return out

    def test_moe_model_trains(self):
        net = MultiLayerNetwork(self._conf()).init()
        net.fit(ListDataSetIterator(self._batches(16)), epochs=8)
        acc = net.evaluate(ListDataSetIterator(self._batches(1, batch=64, seed=5))).accuracy()
        assert acc > 0.75, acc

    def test_expert_parallel_training_on_mesh(self):
        """dp x ep: batch over 'data', expert-stacked weights over 'expert';
        matches the dp-only result (EP is a layout, not a math change)."""
        from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh

        mesh = make_mesh(8, axis_names=("data", "expert"), shape=(4, 2))
        net = MultiLayerNetwork(self._conf()).init()
        wrapper = ParallelWrapper(net, mesh=mesh, expert_axis="expert")
        wrapper.fit(ListDataSetIterator(self._batches(8)), epochs=2)
        assert np.isfinite(float(net._last_loss))

        # expert-stacked weights really live sharded over the expert axis
        spec = net.params[0]["W1"].sharding.spec
        assert spec[0] == "expert", spec
        assert net.params[0]["Wg"].sharding.spec == ()  # gate replicated

        # numerics match a plain dp-only run of the same schedule: the EP
        # wrapper groups `data`-axis-many (4) minibatches per global step, so
        # the dp-only baseline must too
        net2 = MultiLayerNetwork(self._conf()).init()
        wrapper2 = ParallelWrapper(net2, workers=4)
        wrapper2.fit(ListDataSetIterator(self._batches(8)), epochs=2)
        for a, b in zip(jax.tree_util.tree_leaves(net.params),
                        jax.tree_util.tree_leaves(net2.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestMaskingAndGuards:
    def test_padded_timesteps_claim_no_capacity(self):
        """[B,T] feature masks: pad tokens get no expert slot and zero MoE
        output (residual passes through), so real tokens keep capacity."""
        layer = _layer(capacity_factor=1.0, residual=True, n_out=8)
        it = InputType.recurrent(8, 4)
        params = layer.init_params(jax.random.PRNGKey(0),
                                   InputType.feed_forward(8))
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.normal(size=(4, 4, 8)), jnp.float32)
        mask = jnp.asarray(np.tile([1, 1, 0, 0], (4, 1)), jnp.float32)

        out_masked, _ = layer.apply(params, x, {}, mask=mask)
        # pad rows: residual only (MoE contribution exactly zero)
        np.testing.assert_allclose(np.asarray(out_masked[:, 2:]),
                                   np.asarray(x[:, 2:]), atol=1e-6)
        # real rows: match a run on just the real tokens with the same
        # per-expert capacity
        real = x[:, :2].reshape(-1, 8)
        layer2 = _layer(capacity_factor=2.0, residual=True, n_out=8)
        out_real, _ = layer2.apply(params, real, {})
        np.testing.assert_allclose(
            np.asarray(out_masked[:, :2].reshape(-1, 8)),
            np.asarray(out_real), rtol=1e-4, atol=1e-5)

    def test_sharding_axis_typo_raises(self):
        from deeplearning4j_tpu.parallel import make_mesh
        from deeplearning4j_tpu.parallel.sharding import param_shardings

        mesh = make_mesh(8, axis_names=("data", "model"), shape=(4, 2))
        params = {"W": jnp.zeros((4, 8))}
        with pytest.raises(ValueError, match="not in mesh axes"):
            param_shardings(params, mesh, model_axis="modle")
        # expert-only layout: model rules disabled, no error
        shardings = param_shardings(params, mesh, model_axis=None)
        assert shardings["W"].spec == ()

    def test_conv_kernel_not_expert_sharded(self):
        """4-D conv kernels whose height divides the expert axis must NOT
        match the (3-D) expert rule."""
        from deeplearning4j_tpu.parallel import make_mesh
        from deeplearning4j_tpu.parallel.sharding import param_shardings

        mesh = make_mesh(8, axis_names=("data", "expert"), shape=(4, 2))
        params = {"conv": jnp.zeros((4, 4, 3, 16)),
                  "W1": jnp.zeros((4, 8, 16))}
        sh = param_shardings(params, mesh, model_axis=None,
                             expert_axis="expert")
        assert sh["W1"].spec[0] == "expert"
        assert sh["conv"].spec == ()
