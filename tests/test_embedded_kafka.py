"""Kafka-shaped streaming: protocol-faithful embedded broker driving the
KafkaSource seam end to end (reference: EmbeddedKafkaCluster.java +
NDArrayKafkaClient.java + BaseKafkaPipeline.java — the reference proves its
Kafka pipeline against an embedded broker; this suite does the same for the
TPU-native tier, so the kafka-python import gate is the only untested line).
"""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.streaming import (
    EmbeddedKafkaBroker,
    EmbeddedKafkaConsumer,
    EmbeddedKafkaProducer,
    KafkaSource,
    ServeRoute,
    StreamingPipeline,
    TrainRoute,
)
from deeplearning4j_tpu.streaming.embedded_kafka import TopicPartition


def _serialize(features, label=None) -> bytes:
    """NDArray-message wire form for the tests (NDArrayPublisher role)."""
    f = ",".join(repr(float(v)) for v in np.asarray(features).ravel())
    l = "" if label is None else ",".join(
        repr(float(v)) for v in np.asarray(label).ravel())
    return f"{f}|{l}".encode()


def _deserialize(raw: bytes):
    f, l = raw.decode().split("|")
    feats = np.array([float(v) for v in f.split(",")], np.float32)
    label = (None if not l
             else np.array([float(v) for v in l.split(",")], np.float32))
    return feats, label


def test_broker_partitioning_and_offsets():
    broker = EmbeddedKafkaBroker(num_partitions=3)
    prod = EmbeddedKafkaProducer(broker)
    # keyed sends land on one stable partition, in order
    recs = [prod.send("t", f"k{i}".encode(), key=b"same") for i in range(5)]
    assert len({r.partition for r in recs}) == 1
    assert [r.offset for r in recs] == [0, 1, 2, 3, 4]
    # unkeyed sends round-robin across all partitions, per topic (an
    # interleaved second topic must not skew the first topic's rotation)
    parts = []
    for _ in range(6):
        parts.append(prod.send("t2", b"x").partition)
        prod.send("t3", b"y")
    assert parts == [0, 1, 2, 0, 1, 2]


def test_consumer_poll_contract():
    """poll returns {TopicPartition: [ConsumerRecord]} with offsets
    advancing, honours max_records, and drains fairly across partitions."""
    broker = EmbeddedKafkaBroker(num_partitions=2)
    prod = EmbeddedKafkaProducer(broker)
    for i in range(10):
        prod.send("topic-a", str(i).encode())  # round-robin: 5 per partition
    cons = EmbeddedKafkaConsumer("topic-a", broker=broker, group_id="g1")
    assert cons.assignment() == [TopicPartition("topic-a", 0),
                                 TopicPartition("topic-a", 1)]

    batch = cons.poll(max_records=4)
    got = [r for recs in batch.values() for r in recs]
    assert len(got) == 4
    for tp, recs in batch.items():
        assert isinstance(tp, TopicPartition)
        for r in recs:
            assert r.topic == "topic-a" and r.partition == tp.partition
        assert [r.offset for r in recs] == list(range(len(recs)))
        assert cons.position(tp) == len(recs)

    rest = []
    while True:
        b = cons.poll(max_records=100)
        if not b:
            break
        rest.extend(r for recs in b.values() for r in recs)
    assert len(got) + len(rest) == 10
    values = sorted(int(r.value) for r in got + rest)
    assert values == list(range(10))


def test_consumer_seek_commit_and_latest_reset():
    broker = EmbeddedKafkaBroker(num_partitions=1)
    prod = EmbeddedKafkaProducer(broker)
    tp = TopicPartition("t", 0)
    for i in range(4):
        prod.send("t", str(i).encode())

    cons = EmbeddedKafkaConsumer("t", broker=broker)
    assert len(next(iter(cons.poll(max_records=10).values()))) == 4
    cons.commit()
    assert cons.committed(tp).offset == 4
    cons.seek(tp, 1)
    replay = next(iter(cons.poll(max_records=10).values()))
    assert [int(r.value) for r in replay] == [1, 2, 3]
    with pytest.raises(ValueError):
        cons.seek(tp, -1)  # kafka rejects negative offsets

    # auto_offset_reset="latest" starts at the end: only new messages
    late = EmbeddedKafkaConsumer("t", broker=broker,
                                 auto_offset_reset="latest")
    assert late.poll(max_records=10) == {}
    prod.send("t", b"9")
    assert [int(r.value)
            for r in next(iter(late.poll(max_records=10).values()))] == [9]

    cons.close()
    with pytest.raises(RuntimeError):
        cons.poll()


def test_kafka_source_streams_records_through_pipeline():
    """The full reference pipeline shape — producer publishes NDArray
    messages to a partitioned topic; KafkaSource (the real seam, via
    consumer_factory) feeds StreamingPipeline; TrainRoute fits online and
    ServeRoute publishes predictions (BaseKafkaPipeline.java:40-94)."""
    from tests.test_servers_streaming import _toy_data, _toy_net

    broker = EmbeddedKafkaBroker(num_partitions=2)
    prod = EmbeddedKafkaProducer(broker)
    feats, labels = _toy_data(n=96)
    # publish the backlog first (earliest-reset consumers replay it), so
    # the first micro-batch assembles full regardless of host load
    for f, l in zip(feats, labels):
        prod.send("ndarray-topic", _serialize(f, l))

    src = KafkaSource(
        "ndarray-topic", _deserialize,
        consumer_factory=lambda topic, **kw: EmbeddedKafkaConsumer(
            topic, **kw),
        broker=broker, group_id="dl4j", auto_offset_reset="earliest",
    )
    net = _toy_net(lr=0.1)
    train = TrainRoute(net)
    served = []
    serve = ServeRoute(net, sink=lambda x, y: served.append(y))
    pipeline = StreamingPipeline(src, [train, serve], batch=32, linger=1.0)

    def produce_live_tail():
        # records published while the pump is running arrive too (a live
        # topic, not just a replay)
        for f, l in zip(feats[:32], labels[:32]):
            prod.send("ndarray-topic", _serialize(f, l))
            time.sleep(0.001)

    producer_thread = threading.Thread(target=produce_live_tail)
    with pipeline:
        producer_thread.start()
        deadline = time.time() + 60
        while train.batches_seen < 4 and time.time() < deadline:
            time.sleep(0.05)
    producer_thread.join()
    assert train.batches_seen >= 4  # 3 backlog batches + the live tail
    assert len(served) >= 4 and served[0].shape == (32, 3)
    assert src._consumer.closed  # pipeline.stop() closed the consumer


def test_kafka_source_unlabelled_inference_stream():
    """Label-free messages (the serving-only route) flow as features-only
    records — KafkaSource's deserializer contract supports both."""
    broker = EmbeddedKafkaBroker(num_partitions=1)
    prod = EmbeddedKafkaProducer(broker)
    for i in range(3):
        prod.send("serve", _serialize(np.full(8, float(i))))
    src = KafkaSource(
        "serve", _deserialize,
        consumer_factory=lambda topic, **kw: EmbeddedKafkaConsumer(
            topic, **kw),
        broker=broker,
    )
    recs = [src.poll() for _ in range(3)]
    assert all(l is None for _, l in recs)
    assert [int(f[0]) for f, _ in recs] == [0, 1, 2]
    assert src.poll() is None
    src.close()
