"""UI internationalization (reference: deeplearning4j-play I18N.java /
DefaultI18N.java / I18NProvider.java + the dl4j_i18n properties resources
and the Play setlang route)."""

import json
import urllib.request

from deeplearning4j_tpu.ui.i18n import I18N, get_instance
from deeplearning4j_tpu.ui.server import UIServer


class TestI18N:
    def test_lookup_and_language_fallback(self):
        i = I18N()
        assert i.get_message("train.nav.overview") == "Overview"
        assert i.get_message("train.nav.overview", "ja") == "概要"
        assert i.get_message("train.nav.overview", "ko") == "개요"
        # key missing from ko falls back to the default language...
        assert i.get_message("train.overview.chart.itertime", "ko") \
            == "Iteration time (ms)"
        # ...and a key missing everywhere falls back to the key itself
        assert i.get_message("no.such.key", "ja") == "no.such.key"

    def test_default_language_switch(self):
        i = I18N()
        assert i.get_default_language() == "en"
        i.set_default_language("de")
        assert i.get_message("train.nav.overview") == "Übersicht"
        # explicit language still wins over the default
        assert i.get_message("train.nav.overview", "ru") == "Общая информация"

    def test_render_substitutes_tokens(self):
        i = I18N()
        html = i.render("<h1>@@train.overview.title@@</h1>"
                        "<a>@@train.nav.model@@</a>", "zh")
        assert html == "<h1>训练概述</h1><a>模型</a>"
        # unbalanced token renders literally rather than corrupting the page
        assert i.render("a @@oops") == "a @@oops"

    def test_properties_loader(self, tmp_path):
        p = tmp_path / "train.custom.fr"
        p.write_text("# comment\ntrain.nav.overview=Aperçu\n"
                     "train.pagetitle = Interface d'entraînement\n",
                     encoding="utf-8")
        i = I18N()
        assert i.load_properties(str(p), "fr") == 2
        assert i.get_message("train.nav.overview", "fr") == "Aperçu"
        assert "fr" in i.languages()

    def test_provider_singleton(self):
        assert get_instance() is get_instance()


class TestServerI18N:
    def test_pages_render_in_requested_language_and_setlang(self):
        server = UIServer(port=0)
        try:
            base = f"http://127.0.0.1:{server.port}"
            en = urllib.request.urlopen(f"{base}/train/overview").read().decode()
            assert "Score vs iteration" in en and "@@" not in en
            ja = urllib.request.urlopen(
                f"{base}/train/overview?lang=ja").read().decode()
            assert "スコア対反復" in ja and "@@" not in ja

            # /setlang/<code> switches the default (302 back to overview)
            req = urllib.request.Request(f"{base}/setlang/ja")
            page = urllib.request.urlopen(req).read().decode()
            assert "スコア対反復" in page
            api = json.loads(urllib.request.urlopen(
                f"{base}/api/i18n").read())
            assert api["default_language"] == "ja"
            assert "ja" in api["languages"]
            assert api["messages"]["train.nav.overview"] == "概要"
        finally:
            get_instance().set_default_language("en")
            server.stop()

    def test_every_page_renders_token_free(self):
        server = UIServer(port=0)
        try:
            base = f"http://127.0.0.1:{server.port}"
            for page in ("overview", "model", "system", "flow",
                         "activations", "tsne"):
                for lang in ("en", "ja", "ko", "de", "ru", "zh"):
                    html = urllib.request.urlopen(
                        f"{base}/train/{page}?lang={lang}").read().decode()
                    assert "@@" not in html, (page, lang)
        finally:
            server.stop()
