"""Line-search optimizer family + dataset export tests (reference: the
Solver/LBFGS/CG tier §2.1 and Spark export plumbing §2.4)."""

import numpy as np
import pytest

from deeplearning4j_tpu import (
    DenseLayer,
    InputType,
    MultiLayerConfiguration,
    MultiLayerNetwork,
    OutputLayer,
    UpdaterConfig,
)
from deeplearning4j_tpu.datasets.export import (
    FileDataSetIterator,
    export_datasets,
    load_dataset,
)
from deeplearning4j_tpu.datasets.iterators import DataSet, NumpyDataSetIterator
from deeplearning4j_tpu.optimize.solvers import (
    LBFGS,
    ConjugateGradient,
    LineGradientDescent,
    Solver,
    back_track_line_search,
)


def _net_and_data(seed=0):
    rng = np.random.default_rng(seed)
    labels = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 90)]
    feats = (labels @ rng.normal(size=(3, 6)) + 0.15 * rng.normal(size=(90, 6))).astype(np.float32)
    conf = MultiLayerConfiguration(
        layers=[
            DenseLayer(n_out=12, activation="tanh"),
            OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.feed_forward(6),
        updater=UpdaterConfig(updater="sgd", learning_rate=0.1),
        seed=seed,
    )
    return MultiLayerNetwork(conf).init(), feats, labels


def test_backtracking_line_search_on_quadratic():
    f = lambda v: float(np.sum(v**2))  # noqa: E731
    x = np.array([2.0, -3.0])
    g = 2 * x
    step, fnew = back_track_line_search(f, x, f(x), g, -g)
    assert step > 0 and fnew < f(x)
    # ascent direction is rejected
    step2, fsame = back_track_line_search(f, x, f(x), g, g)
    assert step2 == 0.0 and fsame == f(x)


@pytest.mark.parametrize("algo_cls", [LBFGS, ConjugateGradient, LineGradientDescent])
def test_batch_optimizers_reduce_loss(algo_cls):
    net, feats, labels = _net_and_data()
    s0 = net.score(DataSet(feats, labels))
    opt = algo_cls(max_iterations=25)
    final = opt.optimize(net, feats, labels)
    assert final < s0 * 0.5
    # params written back: score() agrees with the optimizer's final value
    assert net.score(DataSet(feats, labels)) == pytest.approx(final, rel=1e-2, abs=1e-5)
    # scores monotonically decreasing-ish (line search guarantees descent)
    hist = opt.score_history
    assert hist[0] >= hist[-1]


def test_lbfgs_beats_plain_sgd_steps_on_small_batch():
    net_lbfgs, feats, labels = _net_and_data(seed=1)
    Solver("lbfgs", max_iterations=30).optimize(net_lbfgs, (feats, labels))
    lbfgs_score = net_lbfgs.score(DataSet(feats, labels))

    net_sgd, _, _ = _net_and_data(seed=1)
    for _ in range(30):
        net_sgd.fit(DataSet(feats, labels))
    assert lbfgs_score < net_sgd.score(DataSet(feats, labels))


def test_solver_unknown_algorithm():
    with pytest.raises(ValueError, match="Unknown algorithm"):
        Solver("newton")


def test_export_and_file_iterator_roundtrip(tmp_path):
    rng = np.random.default_rng(2)
    x = rng.normal(size=(40, 5)).astype(np.float32)
    y = rng.normal(size=(40, 2)).astype(np.float32)
    base = NumpyDataSetIterator(x, y, batch=10)
    paths = export_datasets(base, str(tmp_path))
    assert len(paths) == 4
    ds0 = load_dataset(paths[0])
    np.testing.assert_allclose(ds0.features, x[:10])

    it = FileDataSetIterator(str(tmp_path))
    batches = list(it)
    assert len(batches) == 4
    np.testing.assert_allclose(
        np.concatenate([b.features for b in batches]), x
    )
    # host striping: two processes see disjoint halves
    a = FileDataSetIterator(str(tmp_path), process_index=0, process_count=2)
    b = FileDataSetIterator(str(tmp_path), process_index=1, process_count=2)
    assert len(a) == 2 and len(b) == 2
    assert set(a.paths).isdisjoint(b.paths)
    # masks round-trip
    ds_m = DataSet(x[:4].reshape(4, 5), y[:4],
                   features_mask=np.ones((4, 5), np.float32))
    p = export_datasets(iter([ds_m]), str(tmp_path / "m"))
    assert load_dataset(p[0]).features_mask is not None
