"""Data-parallel training on a device mesh: sync all-reduce + periodic averaging.

Reference example: ParallelWrapperMain / parallelwrapper docs. On one TPU chip
or CPU this runs on virtual devices; on a pod slice the SAME code spans every
chip (mesh axes over ICI). Set XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu for an 8-device CPU mesh.
"""

import argparse

import numpy as np


def _net():
    from deeplearning4j_tpu import (
        DenseLayer,
        InputType,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        OutputLayer,
        UpdaterConfig,
    )

    conf = MultiLayerConfiguration(
        layers=[DenseLayer(n_out=32, activation="relu"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
        input_type=InputType.feed_forward(8),
        updater=UpdaterConfig(updater="adam", learning_rate=5e-3),
        seed=7,
    )
    return MultiLayerNetwork(conf).init()


def main(quick: bool = False):
    import jax

    from deeplearning4j_tpu.datasets.iterators import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh
    from deeplearning4j_tpu.parallel.training_master import (
        ParameterAveragingTrainingMaster,
    )

    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev)
    rng = np.random.default_rng(0)
    w = rng.normal(size=(8, 3))
    batches = []
    for _ in range(4 * n_dev):
        x = rng.normal(size=(32, 8)).astype(np.float32)
        batches.append(DataSet(x, np.eye(3, dtype=np.float32)[(x @ w).argmax(-1)]))

    # sync mode: per-step gradient all-reduce (modern default)
    net = _net()
    wrapper = ParallelWrapper(net, mesh=mesh, averaging_frequency=1)
    wrapper.fit(ListDataSetIterator(batches), epochs=4 if quick else 10)
    acc = net.evaluate([batches[0]]).accuracy()
    print(f"sync all-reduce over {n_dev} devices: accuracy={acc:.3f}")
    print("phase timings:", wrapper.timer.breakdown())

    # periodic parameter averaging (Spark-parity mode) behind the
    # TrainingMaster SPI, with per-phase stats
    net2 = _net()
    master = ParameterAveragingTrainingMaster(averaging_frequency=4, mesh=mesh)
    master.execute_training(net2, ListDataSetIterator(batches),
                            epochs=2 if quick else 10)
    print("master stats:", master.get_stats().summary())
    return acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
