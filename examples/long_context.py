"""Long-context and distributed-first features in one walkthrough.

Beyond-reference capabilities (the reference is 2016: TBPTT only): flash
attention (Pallas, O(T) memory), ring-attention sequence parallelism over a
mesh, MoE with expert parallelism, and GPipe pipeline stages — the framework's
dp/tp/sp/ep/pp matrix driven from user code.
"""

import argparse

import numpy as np


def main(quick: bool = False):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import (
        InputType,
        MixtureOfExpertsLayer,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        UpdaterConfig,
    )
    from deeplearning4j_tpu.datasets import BucketingSequenceIterator
    from deeplearning4j_tpu.nn.layers.attention import (
        LayerNormLayer,
        SelfAttentionLayer,
    )
    from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
    from deeplearning4j_tpu.parallel import make_mesh, ring_attention

    rng = np.random.default_rng(0)
    n_dev = len(jax.devices())

    # 1) a causal transformer block with the Pallas flash kernel, trained on
    #    variable-length sequences bucketed to 2 XLA programs
    seqs = []
    for t in [6, 9, 12, 15, 7, 11, 14, 16] * (1 if quick else 4):
        f = rng.normal(size=(t, 8)).astype(np.float32)
        lab = np.eye(3, dtype=np.float32)[(f.sum(-1) > 0).astype(int)]
        seqs.append((f, lab))
    conf = MultiLayerConfiguration(
        layers=[
            SelfAttentionLayer(n_out=16, n_heads=4, causal=True,
                               attention_impl="flash"),
            LayerNormLayer(),
            MixtureOfExpertsLayer(n_out=16, n_experts=4, hidden=32,
                                  capacity_factor=2.0, residual=True),
            RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.recurrent(8),
        updater=UpdaterConfig(updater="adam", learning_rate=3e-3),
        seed=1,
    )
    net = MultiLayerNetwork(conf).init()
    it = BucketingSequenceIterator(seqs, batch=2, boundaries=(8, 16),
                                   drop_remainder=True)
    net.fit(it, epochs=2 if quick else 10)
    print(f"flash+MoE transformer loss: {float(net._last_loss):.4f} "
          f"(<= {it.num_programs()} compiled programs)")

    # 2) the same attention math sequence-parallel over the mesh: K/V shards
    #    circulate an ICI ring — arbitrarily long sequences
    T = 4 * n_dev
    q = jnp.asarray(rng.normal(size=(2, 4, T, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 4, T, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 4, T, 8)), jnp.float32)
    seq_mesh = make_mesh(n_dev, axis_names=("seq",))
    out = ring_attention(q, k, v, seq_mesh, causal=True)
    print(f"ring attention over {n_dev} devices: out {out.shape}, "
          f"finite={bool(jnp.isfinite(out).all())}")
    return float(net._last_loss)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
