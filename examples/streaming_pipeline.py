"""Streaming micro-batch training + serving (the dl4j-streaming workflow).

Reference example: the camel-kafka streaming pipelines (dl4j-streaming) —
records flow from a source through micro-batching into a TRAIN route
(online fit) and a SERVE route (predictions to a sink), concurrently. Here
the source is the in-process QueueSource; the Kafka source is the same
`RecordSource` seam with a consumer factory.
"""

import argparse
import time


def main(quick: bool = False) -> float:
    import numpy as np

    from deeplearning4j_tpu import (
        DenseLayer,
        InputType,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        OutputLayer,
        UpdaterConfig,
    )
    from deeplearning4j_tpu.streaming import (
        QueueSource,
        ServeRoute,
        StreamingPipeline,
        TrainRoute,
    )

    rng = np.random.default_rng(0)
    w = rng.normal(size=(6, 3))

    net = MultiLayerNetwork(MultiLayerConfiguration(
        layers=[DenseLayer(n_out=24, activation="relu"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
        input_type=InputType.feed_forward(6),
        updater=UpdaterConfig(updater="adam", learning_rate=5e-3),
        seed=3,
    )).init()

    served = []
    batch = 32
    source = QueueSource()
    pipeline = StreamingPipeline(
        source,
        routes=[TrainRoute(net), ServeRoute(net, lambda x, p: served.append(p))],
        batch=batch,
    ).start()

    # producer: stream labeled records in, as a Kafka consumer would
    n = 600 if quick else 3000
    for _ in range(n):
        pipeline.raise_if_failed()  # surface route errors, not "queue full"
        x = rng.normal(size=6).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[(x @ w).argmax()]
        source.put(x, y)
    deadline = time.time() + 60
    while net.iteration < n // batch and time.time() < deadline:
        pipeline.raise_if_failed()
        time.sleep(0.05)
    pipeline.stop()

    # the online-trained model now classifies the stream's concept
    xt = rng.normal(size=(300, 6)).astype(np.float32)
    acc = float((np.asarray(net.output(xt)).argmax(-1) == (xt @ w).argmax(-1)).mean())
    print(f"streamed {n} records -> {net.iteration} online steps, "
          f"served {len(served)} prediction batches, accuracy={acc:.3f}")
    return acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
