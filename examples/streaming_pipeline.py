"""Streaming micro-batch training + serving (the dl4j-streaming workflow).

Reference example: the camel-kafka streaming pipelines (dl4j-streaming) —
records flow from a source through micro-batching into a TRAIN route
(online fit) and a SERVE route (predictions to a sink), concurrently. Two
modes:

- default: in-process QueueSource (the 'direct:' Camel route);
- ``--two-process``: the producer runs as a SEPARATE OS process publishing
  records over TCP (SocketRecordSink -> SocketRecordSource), which is the
  reference's Kafka-between-JVMs topology with the broker replaced by the
  framework's own length-prefixed socket transport;
- ``--kafka``: records flow through a partitioned, offset-addressed
  embedded broker via the kafka-python-shaped consumer surface
  (EmbeddedKafkaBroker/Producer/Consumer + KafkaSource) — the
  BaseKafkaPipeline topology itself; swap the consumer_factory for
  kafka-python and the same code talks to a real cluster.
"""

import argparse
import os
import subprocess
import sys
import time

_PRODUCER_SNIPPET = """
import sys
import numpy as np
from deeplearning4j_tpu.streaming import serve_records

host, port, n = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
rng = np.random.default_rng(0)
w = rng.normal(size=(6, 3))
xs = rng.normal(size=(n, 6)).astype(np.float32)
ys = np.eye(3, dtype=np.float32)[(xs @ w).argmax(-1)]
serve_records(host, port, list(zip(xs, ys)))
print("PRODUCER_OK", flush=True)
"""


def main(quick: bool = False, two_process: bool = False,
         kafka: bool = False) -> float:
    import numpy as np

    from deeplearning4j_tpu import (
        DenseLayer,
        InputType,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        OutputLayer,
        UpdaterConfig,
    )
    from deeplearning4j_tpu.streaming import (
        EmbeddedKafkaBroker,
        EmbeddedKafkaConsumer,
        EmbeddedKafkaProducer,
        KafkaSource,
        QueueSource,
        ServeRoute,
        SocketRecordSource,
        StreamingPipeline,
        TrainRoute,
    )

    rng = np.random.default_rng(0)
    w = rng.normal(size=(6, 3))

    net = MultiLayerNetwork(MultiLayerConfiguration(
        layers=[DenseLayer(n_out=24, activation="relu"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
        input_type=InputType.feed_forward(6),
        updater=UpdaterConfig(updater="adam", learning_rate=5e-3),
        seed=3,
    )).init()

    served = []
    batch = 32
    n = 600 if quick else 3000
    broker = prod = None
    if kafka:
        broker = EmbeddedKafkaBroker(num_partitions=2)
        prod = EmbeddedKafkaProducer(broker)

        def _deser(raw):
            fs, ls = raw.decode().split("|")
            return (np.array([float(v) for v in fs.split(",")], np.float32),
                    np.array([float(v) for v in ls.split(",")], np.float32))

        # the class itself is the factory — swap in kafka.KafkaConsumer
        # (and drop broker=) to talk to a real cluster
        source = KafkaSource("records", _deser,
                             consumer_factory=EmbeddedKafkaConsumer,
                             broker=broker)
    else:
        source = SocketRecordSource() if two_process else QueueSource()
    pipeline = StreamingPipeline(
        source,
        routes=[TrainRoute(net), ServeRoute(net, lambda x, p: served.append(p))],
        batch=batch,
    ).start()

    if two_process:
        # producer OS process publishes over TCP (Kafka-producer role)
        env = dict(os.environ)
        env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
                             + os.pathsep + env.get("PYTHONPATH", ""))
        proc = subprocess.Popen(
            [sys.executable, "-c", _PRODUCER_SNIPPET,
             source.host, str(source.port), str(n)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        out, _ = proc.communicate(timeout=300)
        assert proc.returncode == 0 and "PRODUCER_OK" in out, out[-2000:]
    elif kafka:
        # publish NDArray messages to the partitioned topic (the
        # NDArrayPublisher role); the consumer replays from earliest
        for _ in range(n):
            pipeline.raise_if_failed()
            x = rng.normal(size=6).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[(x @ w).argmax()]
            payload = (",".join(map(repr, x.tolist())) + "|"
                       + ",".join(map(repr, y.tolist()))).encode()
            prod.send("records", payload)
    else:
        # producer thread-in-process: stream labeled records in
        for _ in range(n):
            pipeline.raise_if_failed()  # surface route errors, not "queue full"
            x = rng.normal(size=6).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[(x @ w).argmax()]
            source.put(x, y)
    deadline = time.time() + 60
    while net.iteration < n // batch and time.time() < deadline:
        pipeline.raise_if_failed()
        time.sleep(0.05)
    pipeline.stop()

    # the online-trained model now classifies the stream's concept
    xt = rng.normal(size=(300, 6)).astype(np.float32)
    acc = float((np.asarray(net.output(xt)).argmax(-1) == (xt @ w).argmax(-1)).mean())
    mode = ("embedded kafka" if kafka
            else "two-process socket" if two_process else "in-process")
    print(f"[{mode}] streamed {n} records -> {net.iteration} online steps, "
          f"served {len(served)} prediction batches, accuracy={acc:.3f}")
    return acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--two-process", action="store_true")
    ap.add_argument("--kafka", action="store_true")
    args = ap.parse_args()
    main(args.quick, args.two_process, args.kafka)
