"""Transfer learning: freeze a trained feature extractor, replace the head.

Reference example: dl4j-examples transfer-learning set (EditLastLayerOthersFrozen):
train a base model on task A, freeze everything below the head, swap in a new
output layer for task B, fine-tune — frozen params provably unchanged.
"""

import argparse

import numpy as np


def main(quick: bool = False):
    import jax

    from deeplearning4j_tpu import (
        DenseLayer,
        InputType,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        OutputLayer,
        UpdaterConfig,
    )
    from deeplearning4j_tpu.datasets.iterators import DataSet
    from deeplearning4j_tpu.nn.transferlearning import TransferLearning

    rng = np.random.default_rng(0)
    w = rng.normal(size=(10, 4))

    def task(n_classes, seed, n=256):
        r = np.random.default_rng(seed)
        x = r.normal(size=(n, 10)).astype(np.float32)
        y = (x @ w[:, :n_classes]).argmax(-1)
        return DataSet(x, np.eye(n_classes, dtype=np.float32)[y])

    conf = MultiLayerConfiguration(
        layers=[
            DenseLayer(n_out=32, activation="relu"),
            DenseLayer(n_out=16, activation="relu"),
            OutputLayer(n_out=4, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.feed_forward(10),
        updater=UpdaterConfig(updater="adam", learning_rate=5e-3),
        seed=1,
    )
    base = MultiLayerNetwork(conf).init()
    base.fit(task(4, seed=0), epochs=40 if quick else 60)
    print("base task accuracy:", round(base.evaluate(task(4, seed=9)).accuracy(), 3))

    # freeze layers 0-1, replace the 4-way head with a 3-way head
    new_net = (
        TransferLearning.Builder(base)
        .set_feature_extractor(1)
        .remove_output_layer()
        .add_layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .build()
    )
    frozen_before = jax.tree_util.tree_map(np.asarray, new_net.params[0])
    new_net.fit(task(3, seed=2), epochs=40 if quick else 60)
    frozen_after = jax.tree_util.tree_map(np.asarray, new_net.params[0])
    for a, b in zip(jax.tree_util.tree_leaves(frozen_before),
                    jax.tree_util.tree_leaves(frozen_after)):
        np.testing.assert_array_equal(a, b)
    acc = new_net.evaluate(task(3, seed=11)).accuracy()
    print("new task accuracy (frozen features):", round(acc, 3))
    return acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
