"""Word2Vec on raw text: vocab build, training, nearest-word queries.

Reference example: dl4j-examples Word2VecRawTextExample.
"""

import argparse

SENTENCES = [
    "the king rules the kingdom",
    "the queen rules the kingdom",
    "the king and the queen sit on thrones",
    "a dog chases the cat",
    "the cat runs from the dog",
    "dogs and cats are animals",
    "the kingdom has a castle",
    "the castle belongs to the king and queen",
] * 6


def main(quick: bool = False):
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    w2v = Word2Vec(
        layer_size=16 if quick else 64,
        window=3,
        min_word_frequency=2,
        epochs=1 if quick else 5,
        seed=42,
    )
    w2v.fit(SENTENCES)
    print("vocab size:", len(list(w2v.vocab.words())))
    near = w2v.words_nearest("king", top_n=3)
    print("nearest to 'king':", near)
    sim = w2v.similarity("king", "queen")
    print(f"similarity(king, queen) = {sim:.3f}")
    return near


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
