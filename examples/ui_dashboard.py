"""Training dashboard: StatsListener -> StatsStorage -> UIServer.

Reference example: dl4j-examples UIExample (UIServer.getInstance().attach).
Serves overview / model / system / flow / activations / t-SNE pages while a
small CNN trains; in --quick mode trains, asserts the endpoints respond, and
exits.
"""

import argparse
import json
import urllib.request

import numpy as np


def main(quick: bool = False):
    from deeplearning4j_tpu import (
        DenseLayer,
        InputType,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        OutputLayer,
        UpdaterConfig,
    )
    from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
    from deeplearning4j_tpu.nn.layers.convolution import ConvolutionLayer
    from deeplearning4j_tpu.ui import (
        ConvolutionalIterationListener,
        InMemoryStatsStorage,
        StatsListener,
        UIServer,
    )

    storage = InMemoryStatsStorage()
    server = UIServer(port=0 if quick else 9000)
    server.attach(storage)
    print(f"dashboard: http://127.0.0.1:{server.port}/train/overview")

    conf = MultiLayerConfiguration(
        layers=[
            ConvolutionLayer(n_out=8, kernel=(3, 3), activation="relu"),
            DenseLayer(n_out=64, activation="relu"),
            OutputLayer(n_out=10, activation="softmax"),
        ],
        input_type=InputType.convolutional(8, 8, 1),
        updater=UpdaterConfig(updater="adam", learning_rate=2e-3),
    )
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(
        StatsListener(storage, session_id="ui_example"),
        ConvolutionalIterationListener(storage, frequency=5, session_id="ui_example"),
    )
    net.fit(DigitsDataSetIterator(batch=128, train=True), epochs=2 if quick else 20)

    base = f"http://127.0.0.1:{server.port}"
    h = json.loads(urllib.request.urlopen(
        f"{base}/api/histograms?session=ui_example").read())
    assert h["param_histograms"], "no histograms recorded"
    a = json.loads(urllib.request.urlopen(
        f"{base}/api/activations?session=ui_example").read())
    assert a.get("conv_activations", {}).get("maps"), "no feature maps"
    print("endpoints OK: histograms + activations populated")
    if quick:
        server.stop()
    else:  # leave serving for a browser
        input("dashboard running — press Enter to stop")
        server.stop()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
