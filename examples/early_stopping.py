"""Early stopping with score calculator, termination conditions, model saver.

Reference example: dl4j-examples EarlyStoppingMnistExample.
"""

import argparse
import tempfile

import numpy as np


def main(quick: bool = False):
    from deeplearning4j_tpu import (
        DenseLayer,
        InputType,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        OutputLayer,
        UpdaterConfig,
    )
    from deeplearning4j_tpu.datasets.iterators import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.earlystopping import (
        DataSetLossCalculator,
        EarlyStoppingConfiguration,
        EarlyStoppingTrainer,
        LocalFileModelSaver,
        MaxEpochsTerminationCondition,
        ScoreImprovementEpochTerminationCondition,
    )

    rng = np.random.default_rng(3)
    w = rng.normal(size=(6, 3))

    def batches(n, seed):
        r = np.random.default_rng(seed)
        out = []
        for _ in range(n):
            x = r.normal(size=(32, 6)).astype(np.float32)
            out.append(DataSet(x, np.eye(3, dtype=np.float32)[(x @ w).argmax(-1)]))
        return out

    conf = MultiLayerConfiguration(
        layers=[DenseLayer(n_out=24, activation="tanh"),
                OutputLayer(n_out=3, activation="softmax", loss="mcxent")],
        input_type=InputType.feed_forward(6),
        updater=UpdaterConfig(updater="adam", learning_rate=5e-3),
    )
    net = MultiLayerNetwork(conf).init()

    save_dir = tempfile.mkdtemp()
    es_conf = EarlyStoppingConfiguration(
        epoch_termination_conditions=[
            MaxEpochsTerminationCondition(4 if quick else 30),
            ScoreImprovementEpochTerminationCondition(patience=5),
        ],
        score_calculator=DataSetLossCalculator(ListDataSetIterator(batches(4, 99))),
        model_saver=LocalFileModelSaver(save_dir),
    )
    trainer = EarlyStoppingTrainer(es_conf, net, ListDataSetIterator(batches(8, 0)))
    result = trainer.fit()
    print("termination reason:", result.termination_reason)
    print("best epoch:", result.best_model_epoch,
          "best score:", round(result.best_model_score, 5))
    best = result.best_model
    assert best is not None
    return result


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
