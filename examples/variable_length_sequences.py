"""Variable-length sequence training + streaming with bounded recompiles.

Reference analog: SequenceRecordReaderDataSetIterator's padded/aligned
batches over ragged sequence data. On TPU the extra constraint is XLA's
one-program-per-shape compilation (SURVEY §7 hard part f): a naive
pad-to-batch-max pipeline compiles once per distinct length — a recompile
storm on real text. This example shows the framework's answer end to end:

1. train a sequence classifier over a RAGGED corpus (27+ distinct lengths)
   through ``BucketingSequenceIterator`` — every epoch runs in at most
   ``num_programs()`` compiled programs;
2. stream variable-length inputs through stateful ``rnn_time_step`` with
   ``pad_to_bucket`` + the features mask — one program per bucket, and the
   carried LSTM state is exactly the real sequence's (masked steps hold
   h/c).

The task: classify whether a noisy sine sequence has high or low frequency
— only solvable by actually reading the time dimension.
"""

import argparse

import numpy as np


def make_corpus(n, rng, t_lo=6, t_hi=40):
    """Ragged [T_i, 1] sine sequences; label = high vs low frequency."""
    seqs = []
    for _ in range(n):
        t = int(rng.integers(t_lo, t_hi))
        label = int(rng.integers(0, 2))
        freq = 1.4 if label else 0.35
        phase = rng.uniform(0, np.pi)
        x = np.sin(freq * np.arange(t) + phase) + 0.1 * rng.normal(size=t)
        y = np.zeros((t, 2), np.float32)
        y[:, label] = 1.0  # per-step labels, masked to the real steps
        seqs.append((x.astype(np.float32)[:, None], y))
    return seqs


def main(quick: bool = False) -> float:
    from deeplearning4j_tpu import (
        GravesLSTM,
        InputType,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        RnnOutputLayer,
        UpdaterConfig,
    )
    from deeplearning4j_tpu.datasets.iterators import (
        BucketingSequenceIterator,
        pad_to_bucket,
    )

    rng = np.random.default_rng(7)
    bounds = (8, 16, 24, 40)
    corpus = make_corpus(120 if quick else 400, rng)
    it = BucketingSequenceIterator(corpus, batch=16, boundaries=bounds)

    conf = MultiLayerConfiguration(
        layers=[
            GravesLSTM(n_out=16, activation="tanh"),
            RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.recurrent(1),
        updater=UpdaterConfig(updater="adam", learning_rate=1e-2),
        seed=3,
    )
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=4 if quick else 12)
    compiles = net._train_step._cache_size()
    assert compiles <= it.num_programs(), (compiles, it.num_programs())

    # streaming inference over ragged inputs: one program per bucket, state
    # held through the padded tail
    test = make_corpus(60, rng)
    correct = 0
    for feats, labels in test:
        net.rnn_clear_previous_state()
        xp, mask, t = pad_to_bucket(feats[None, ...], bounds)
        out = np.asarray(net.rnn_time_step(xp, features_mask=mask))[0, :t]
        pred = out.mean(axis=0).argmax()
        correct += int(pred == labels[0].argmax())
    acc = correct / len(test)
    # PR 7: streaming programs are AOT entries in the process compile
    # manager (keyed by the net's owner token), not a per-net jit cache
    from deeplearning4j_tpu.runtime.compile_manager import get_compile_manager

    cm = get_compile_manager()
    stream_programs = len([
        k for k in cm._entries
        if isinstance(k, tuple) and k and k[0] == net._cm_token
        and cm._key_kind(k) == "mln_rnn_step"])
    assert stream_programs <= len(bounds), stream_programs
    distinct = len({f.shape[0] for f, _ in corpus})
    print(
        f"ragged corpus: {distinct} distinct lengths -> "
        f"{compiles} train programs (bound {it.num_programs()}), "
        f"{stream_programs} streaming programs (bound {len(bounds)}); "
        f"held-out accuracy={acc:.3f}"
    )
    return acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
