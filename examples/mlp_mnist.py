"""MLP on MNIST: fit / evaluate / checkpoint round-trip.

Reference example: dl4j-examples MLPMnistSingleLayerExample (the canonical
first program). Uses real MNIST when present (MNIST_DIR / fetch_mnist),
deterministic synthetic otherwise.
"""

import argparse
import os
import tempfile


def main(quick: bool = False) -> float:
    from deeplearning4j_tpu import (
        DenseLayer,
        InputType,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        OutputLayer,
        ScoreIterationListener,
        UpdaterConfig,
    )
    from deeplearning4j_tpu.datasets.fetchers import MnistDataSetIterator
    from deeplearning4j_tpu.utils.serialization import restore_model, write_model

    conf = MultiLayerConfiguration(
        layers=[
            DenseLayer(n_out=256, activation="relu"),
            OutputLayer(n_out=10, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.feed_forward(784),
        updater=UpdaterConfig(updater="adam", learning_rate=1e-3),
        seed=123,
    )
    net = MultiLayerNetwork(conf).init()
    net.add_listener(ScoreIterationListener(print_every=50))

    n = 1024 if quick else None
    train = MnistDataSetIterator(batch=128, train=True, num_examples=n)
    net.fit(train, epochs=5 if quick else 5)

    # quick mode may be running on the synthetic fallback corpus, whose train
    # and test splits are drawn from different templates — score the train
    # split there; with real MNIST the held-out split is the number to watch
    test = MnistDataSetIterator(batch=256, train=quick, shuffle=False,
                                num_examples=512 if quick else None)
    ev = net.evaluate(test)
    print(ev.stats())

    path = os.path.join(tempfile.mkdtemp(), "mlp_mnist.zip")
    write_model(net, path)
    restored = restore_model(path)
    assert restored.evaluate(test).accuracy() == ev.accuracy()
    print(f"checkpoint round-trip OK: {path}")
    return ev.accuracy()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
