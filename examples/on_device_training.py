"""On-device training loop: N optimizer steps in ONE device dispatch.

The TPU-first answer to the reference's per-minibatch fit loop
(MultiLayerNetwork.fit:917): `fit_on_device` stages K batches in HBM and
`lax.scan`s the jitted train step over them, so the host dispatches once per
LOOP instead of once per STEP. On a network-attached TPU each dispatch costs
an RPC round-trip that can exceed the step itself (BASELINE.md methodology
notes); on any TPU it removes the host from the hot path entirely. Numerics
are bit-identical to per-step fit — same RNG split chain — which this
example verifies, then shows the same API running data-parallel over the
whole mesh via ParallelWrapper (gradient psums ride ICI *inside* the scan).
"""

import argparse

import numpy as np


def _conf(seed=7):
    from deeplearning4j_tpu import (
        DenseLayer,
        InputType,
        MultiLayerConfiguration,
        OutputLayer,
        UpdaterConfig,
    )

    return MultiLayerConfiguration(
        layers=[DenseLayer(n_out=64, activation="relu"),
                OutputLayer(n_out=5, activation="softmax", loss="mcxent")],
        input_type=InputType.feed_forward(12),
        updater=UpdaterConfig(updater="adam", learning_rate=3e-3),
        seed=seed,
    )


def main(quick: bool = False):
    import jax

    from deeplearning4j_tpu import MultiLayerNetwork
    from deeplearning4j_tpu.datasets.iterators import DataSet
    from deeplearning4j_tpu.parallel import ParallelWrapper, make_mesh

    rng = np.random.default_rng(0)
    w = rng.normal(size=(12, 5))
    k, b = 8, 64  # K staged batches of b examples
    xs = rng.normal(size=(k, b, 12)).astype(np.float32)
    ys = np.eye(5, dtype=np.float32)[(xs @ w).argmax(-1)]
    steps = 2 * k if quick else 10 * k  # cycles i % K through the batches

    # 1) one dispatch for the whole loop
    net = MultiLayerNetwork(_conf()).init()
    losses = net.fit_on_device(xs, ys, steps=steps)
    acc = net.evaluate([DataSet(xs[0], ys[0])]).accuracy()
    print(f"on-device loop: {steps} steps in 1 dispatch, "
          f"loss {losses[0]:.3f} -> {losses[-1]:.3f}, accuracy={acc:.3f}")

    # 2) bit-parity with the sequential per-step path
    seq = MultiLayerNetwork(_conf()).init()
    for i in range(steps):
        seq.fit(DataSet(xs[i % k], ys[i % k]))
    for a, s in zip(jax.tree_util.tree_leaves(net.params),
                    jax.tree_util.tree_leaves(seq.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(s),
                                   atol=1e-6, rtol=1e-5)
    print("parity: on-device params == sequential params")

    # 3) same API, data-parallel over the mesh: batch dim shards over the
    # "data" axis; gradient all-reduce happens inside the scanned step
    n_dev = len(jax.devices())
    dp_net = MultiLayerNetwork(_conf()).init()
    wrapper = ParallelWrapper(dp_net, mesh=make_mesh(n_dev), averaging_frequency=1)
    dp_losses = wrapper.fit_on_device(xs, ys, steps=steps)
    print(f"data-parallel over {n_dev} devices: "
          f"loss {dp_losses[0]:.3f} -> {dp_losses[-1]:.3f}; "
          f"phase timings: {wrapper.timer.breakdown()}")
    return acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
