"""Import a Keras HDF5 model, verify its predictions, and fine-tune it.

Reference example: the modelimport workflow (KerasModelImport.
importKerasModelAndWeights) — a model trained elsewhere in Keras drops into
this framework for inference and continued training. Since this image has no
Keras, the script writes a Keras-1.x-format HDF5 itself (the exact archive
layout the importer reads) — substitute any real .h5 path.
"""

import argparse
import json
import os
import tempfile

import numpy as np


def _make_keras_h5(path: str, rng) -> tuple:
    import h5py

    W1 = rng.normal(size=(6, 16)).astype(np.float32)
    b1 = np.zeros(16, np.float32)
    W2 = rng.normal(size=(16, 3)).astype(np.float32)
    b2 = np.zeros(3, np.float32)
    model_config = {
        "class_name": "Sequential",
        "config": [
            {"class_name": "Dense",
             "config": {"name": "dense_1", "output_dim": 16,
                        "activation": "relu", "bias": True,
                        "batch_input_shape": [None, 6]}},
            {"class_name": "Dense",
             "config": {"name": "dense_2", "output_dim": 3,
                        "activation": "softmax", "bias": True}},
        ],
    }
    training_config = {
        "optimizer_config": {"class_name": "Adam", "config": {"lr": 1e-3}},
        "loss": "categorical_crossentropy",
    }
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(model_config).encode()
        f.attrs["training_config"] = json.dumps(training_config).encode()
        g = f.create_group("model_weights")
        g.attrs["layer_names"] = np.array([b"dense_1", b"dense_2"], dtype="S64")
        for lname, weights in {
            "dense_1": [("dense_1_W", W1), ("dense_1_b", b1)],
            "dense_2": [("dense_2_W", W2), ("dense_2_b", b2)],
        }.items():
            lg = g.create_group(lname)
            lg.attrs["weight_names"] = np.array(
                [wn.encode() for wn, _ in weights], dtype="S64")
            for wn, arr in weights:
                lg.create_dataset(wn, data=arr)
    return W1, b1, W2, b2


def main(quick: bool = False) -> float:
    from deeplearning4j_tpu.datasets.iterators import DataSet
    from deeplearning4j_tpu.modelimport.keras import (
        import_keras_sequential_model_and_weights,
    )

    rng = np.random.default_rng(0)
    path = os.path.join(tempfile.mkdtemp(), "keras_mlp.h5")
    W1, b1, W2, b2 = _make_keras_h5(path, rng)

    net = import_keras_sequential_model_and_weights(path)
    print(f"imported: {[type(l).__name__ for l in net.conf.layers]}, "
          f"updater={net.conf.updater.updater}")

    # predictions must equal the source model's math exactly
    x = rng.normal(size=(8, 6)).astype(np.float32)
    h = np.maximum(x @ W1 + b1, 0.0)
    z = h @ W2 + b2
    expect = np.exp(z - z.max(-1, keepdims=True))
    expect /= expect.sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(net.output(x)), expect,
                               rtol=1e-4, atol=1e-5)
    print("imported predictions match the source weights")

    # ...and training continues from the imported state
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    net.fit(DataSet(x, y), epochs=2 if quick else 10)
    loss = float(net._last_loss)
    print(f"fine-tuned loss: {loss:.4f}")
    return loss


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
