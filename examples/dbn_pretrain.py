"""Deep Belief Network: layerwise RBM pretraining, then fine-tuning.

Reference example: the workflow the reference project was founded on
(DeepBeliefNetworkExample / MnistDBNExample) — greedy CD-k pretraining of a
stacked-RBM feature hierarchy, then supervised backprop through the whole
stack. Runs on the real handwritten-digit corpus bundled with sklearn.
"""

import argparse


def main(quick: bool = False) -> float:
    from deeplearning4j_tpu import MultiLayerNetwork
    from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
    from deeplearning4j_tpu.models import dbn_conf

    conf = dbn_conf(
        n_in=64,                      # 8x8 digit scans, flattened
        layer_sizes=(96, 48),
        n_classes=10,
        visible_unit="gaussian",      # real-valued pixel inputs
        updater="adam",
        learning_rate=2e-3,
        seed=5,
    )
    net = MultiLayerNetwork(conf).init()
    print(net.summary())

    it = DigitsDataSetIterator(batch=128, train=True, flat=True)
    net.pretrain(it, epochs=2 if quick else 5)      # unsupervised CD-k
    net.fit(it, epochs=12 if quick else 25)          # supervised fine-tune
    ev = net.evaluate(
        DigitsDataSetIterator(batch=120, train=False, shuffle=False, flat=True)
    )
    print(ev.stats())
    return ev.accuracy()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
