"""LeNet CNN with real-data accuracy.

Reference example: dl4j-examples LenetMnistExample. Trains on the real
handwritten-digit corpus bundled with sklearn (8x8 scans, kernels scaled
accordingly); the full 28x28 LeNet-5 config (models/lenet.py) drops in when
true MNIST is available.
"""

import argparse


def main(quick: bool = False) -> float:
    from deeplearning4j_tpu import (
        DenseLayer,
        InputType,
        MultiLayerConfiguration,
        MultiLayerNetwork,
        OutputLayer,
        UpdaterConfig,
    )
    from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
    from deeplearning4j_tpu.nn.layers.convolution import ConvolutionLayer
    from deeplearning4j_tpu.nn.layers.pooling import SubsamplingLayer

    conf = MultiLayerConfiguration(
        layers=[
            ConvolutionLayer(n_out=20, kernel=(3, 3), activation="identity"),
            SubsamplingLayer(pooling_type="max", kernel=(2, 2), stride=(2, 2)),
            ConvolutionLayer(n_out=50, kernel=(2, 2), activation="identity"),
            SubsamplingLayer(pooling_type="max", kernel=(2, 2), stride=(2, 2)),
            DenseLayer(n_out=128, activation="relu"),
            OutputLayer(n_out=10, activation="softmax", loss="mcxent"),
        ],
        input_type=InputType.convolutional(8, 8, 1),
        updater=UpdaterConfig(updater="adam", learning_rate=2e-3),
        seed=5,
    )
    net = MultiLayerNetwork(conf).init()
    net.fit(DigitsDataSetIterator(batch=128, train=True), epochs=6 if quick else 12)
    ev = net.evaluate(DigitsDataSetIterator(batch=120, train=False, shuffle=False))
    print(ev.stats())
    return ev.accuracy()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
