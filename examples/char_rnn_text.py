"""Character-level LSTM language model with TBPTT + streaming sampling.

Reference example: dl4j-examples GravesLSTMCharModellingExample — train a
stacked GravesLSTM on text, then generate with stateful rnn_time_step
(one traced program per step, h/c carried across calls).
"""

import argparse

import numpy as np

TEXT = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump! "
) * 8


def main(quick: bool = False) -> str:
    from deeplearning4j_tpu import MultiLayerNetwork
    from deeplearning4j_tpu.datasets.iterators import DataSet
    from deeplearning4j_tpu.models.char_rnn import char_rnn

    vocab = sorted(set(TEXT))
    stoi = {c: i for i, c in enumerate(vocab)}
    ids = np.array([stoi[c] for c in TEXT])

    conf = char_rnn(vocab_size=len(vocab), hidden_size=32 if quick else 128,
                    num_layers=1 if quick else 2, tbptt_length=16)
    net = MultiLayerNetwork(conf).init()

    T, B = 64, 8
    n_wins = (len(ids) - 1) // T
    xs = np.stack([np.eye(len(vocab), dtype=np.float32)[ids[i * T:(i + 1) * T]]
                   for i in range(n_wins)])
    ys = np.stack([np.eye(len(vocab), dtype=np.float32)[ids[i * T + 1:(i + 1) * T + 1]]
                   for i in range(n_wins)])
    for _ in range(2 if quick else 20):
        for s in range(0, n_wins - B + 1, B):
            net.fit(DataSet(xs[s:s + B], ys[s:s + B]))

    # streaming generation: one char at a time, state carried on the net
    net.rnn_clear_previous_state()
    seed = "the "
    out = list(seed)
    rng = np.random.default_rng(0)
    x = np.eye(len(vocab), dtype=np.float32)[[stoi[c] for c in seed]][None, :, :]
    probs = np.asarray(net.rnn_time_step(x))[0, -1]
    for _ in range(40 if quick else 200):
        idx = int(rng.choice(len(vocab), p=probs / probs.sum()))
        out.append(vocab[idx])
        step = np.eye(len(vocab), dtype=np.float32)[[idx]]
        probs = np.asarray(net.rnn_time_step(step))[0]
        if probs.ndim == 2:
            probs = probs[-1]
    text = "".join(out)
    print(text)
    return text


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(ap.parse_args().quick)
